"""Runtime environments: per-task/actor env_vars, py_modules, working_dir.

reference: python/ray/_private/runtime_env/ — envs are applied to DEDICATED
worker processes (the raylet's WorkerPool keys workers by runtime-env hash
and starts new ones with the env baked in), packages are content-addressed
URIs cached in the GCS KV (uri_cache.py), and the per-node agent
materializes them before the lease is granted.  Here the materialization
runs in the worker bootstrap (workers_main) — same contract, one fewer
process.

Supported fields (the reference's core trio):
  env_vars:    {name: value} exported before user code runs
  py_modules:  local dirs/files zipped to the GCS KV (kv://pymod:<sha>),
               extracted on the worker, prepended to sys.path
  working_dir: local dir zipped likewise, extracted + chdir'd
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import tempfile
import zipfile
from typing import Any, Dict, Optional

_KV_PREFIX = "kv://"


_SUPPORTED = ("env_vars", "py_modules", "working_dir", "pip", "uv",
              "worker_process_setup_hook")


def normalize(runtime_env: Optional[dict]) -> Optional[dict]:
    """Canonical form; None for empty (no dedicated worker needed)."""
    if not runtime_env:
        return None
    out = {}
    for key in _SUPPORTED:
        if runtime_env.get(key):
            out[key] = runtime_env[key]
    unknown = set(runtime_env) - set(_SUPPORTED)
    if unknown:
        raise ValueError(f"unsupported runtime_env fields: {sorted(unknown)}")
    if "pip" in out:
        pip = out["pip"]
        if isinstance(pip, dict):  # reference accepts {"packages": [...]}
            pip = pip.get("packages", [])
        if isinstance(pip, str):
            raise ValueError(
                "runtime_env['pip'] must be a list of requirement strings "
                "(requirements-file paths are not supported: the image is "
                "immutable, so this field validates rather than installs)")
        out["pip"] = sorted(str(p) for p in pip)
    if "uv" in out:
        uv = out["uv"]
        find_links = None
        if isinstance(uv, dict):  # reference uv field accepts {"packages"}
            find_links = uv.get("find_links")
            uv = uv.get("packages", [])
        if isinstance(uv, str):
            raise ValueError(
                "runtime_env['uv'] must be a list of requirement strings "
                "or {'packages': [...], 'find_links': dir}")
        if isinstance(runtime_env.get("uv"), dict):
            unknown_uv = set(runtime_env["uv"]) - {"packages", "find_links"}
            if unknown_uv:
                raise ValueError(
                    f"unsupported runtime_env['uv'] keys: {sorted(unknown_uv)}"
                    " (supported: packages, find_links)")
        if not uv:
            raise ValueError(
                "runtime_env['uv'] needs a non-empty 'packages' list")
        spec = {"packages": sorted(str(p) for p in uv)}
        if find_links:
            spec["find_links"] = str(find_links)
        out["uv"] = spec
    hook = out.get("worker_process_setup_hook")
    if hook is not None and not (
            callable(hook)
            or (isinstance(hook, dict) and hook.get("kv"))
            or (isinstance(hook, str) and hook.startswith(_KV_PREFIX))):
        raise ValueError(
            "runtime_env['worker_process_setup_hook'] must be a callable "
            "(it is shipped through the function registry and run once per "
            "worker before its first task), or an already-packaged kv:// "
            "function URI")
    return out or None


def check_pip_requirements(packages) -> None:
    """This deployment's images are IMMUTABLE (decision recorded in
    PARITY.md): runtime_env["pip"] VALIDATES that the requirements are
    already satisfied by the baked image instead of installing — a missing
    or mismatched package fails worker setup with a clear error rather
    than silently running against the wrong environment (reference:
    _private/runtime_env/pip.py installs; same user-visible contract of
    "my task ran with these packages or it didn't run")."""
    import importlib.metadata as im

    try:
        from packaging.requirements import InvalidRequirement, Requirement
        from packaging.version import Version
    except ImportError:  # presence-only fallback
        Requirement = None

    problems = []
    for req in packages:
        req = str(req)
        if Requirement is None:
            name = req.split(";")[0].split("[")[0]
            for sep in ("==", ">=", "<=", "~=", "!=", ">", "<"):
                name = name.split(sep)[0]
            try:
                im.version(name.strip())
            except im.PackageNotFoundError:
                problems.append(f"{name.strip()}: not installed in the immutable image")
            continue
        try:
            r = Requirement(req)
        except InvalidRequirement as e:
            problems.append(f"{req!r}: unparseable requirement ({e})")
            continue
        try:
            have = im.version(r.name)
        except im.PackageNotFoundError:
            problems.append(f"{r.name}: not installed in the immutable image")
            continue
        if r.specifier and not r.specifier.contains(Version(have), prereleases=True):
            problems.append(f"{r.name}: image has {have}, requirement is {r.specifier}")
    if problems:
        raise RuntimeError(
            "runtime_env['pip'] cannot install into the immutable TPU image; "
            "these requirements are unsatisfied: " + "; ".join(problems)
            + ". Bake them into the image or drop the pin.")


def materialize_uv_env(spec: dict) -> str:
    """Create (or reuse) an ephemeral uv venv for ``spec`` and return its
    site-packages dir (VERDICT r4 missing #1; reference capability:
    _private/runtime_env/uv.py / pip.py:45 build real per-env virtualenvs).

    Zero-egress images: uv resolves offline from its local wheel cache
    plus an optional ``find_links`` wheel directory (spec field, or the
    ``RAY_TPU_UV_FIND_LINKS`` env var).  The env is cached under a content
    hash and shared by every worker in the env's pool; concurrent
    materializations race safely via build-then-atomic-rename.

    If resolution fails BUT the immutable image already satisfies every
    requirement, the baked versions are used (validate-only fallback —
    the reference's behavior when an env is a no-op); otherwise a clear
    worker-setup error surfaces both failures.
    """
    import subprocess

    packages = spec.get("packages") or []
    if not packages:
        return ""
    # the EFFECTIVE wheel source is part of the identity: a changed
    # RAY_TPU_UV_FIND_LINKS must not silently reuse a stale venv
    find_links = (spec.get("find_links")
                  or os.environ.get("RAY_TPU_UV_FIND_LINKS"))
    key = hashlib.sha1(json.dumps(
        {"packages": list(packages), "find_links": find_links},
        sort_keys=True).encode()).hexdigest()[:16]
    base = os.path.join(tempfile.gettempdir(), "ray_tpu_uv_envs")
    dest = os.path.join(base, key)

    def site_dir(venv: str) -> str:
        v = f"python{sys.version_info.major}.{sys.version_info.minor}"
        return os.path.join(venv, "lib", v, "site-packages")

    if os.path.exists(os.path.join(dest, ".validate_only")):
        return ""  # cached negative: baked image satisfies the pins
    if os.path.exists(os.path.join(dest, ".ready")):
        return site_dir(dest)
    os.makedirs(base, exist_ok=True)
    staging = tempfile.mkdtemp(prefix=".uv-build-", dir=base)

    def publish(marker: str) -> bool:
        open(os.path.join(staging, marker), "w").close()
        try:
            os.rename(staging, dest)
            return True
        except OSError:  # concurrent build published first
            import shutil

            shutil.rmtree(staging, ignore_errors=True)
            return False

    def published_result() -> str:
        """Resolve against whatever a CONCURRENT build published: losing
        the rename race must adopt the winner's verdict, not return this
        build's (now-deleted) staging dir.  A published dest always
        carries a marker (publish() writes it before the rename):
        ``.ready`` → the winner's venv; ``.validate_only`` → the winner
        proved the baked image satisfies the pins, so use it ('').  A
        markerless dest means the rename failed for a NON-race reason
        (e.g. a tmp cleaner pruned the parent) — fail loudly rather than
        silently run against the baked image unvalidated."""
        if os.path.exists(os.path.join(dest, ".ready")):
            return site_dir(dest)
        if os.path.exists(os.path.join(dest, ".validate_only")):
            return ""
        raise RuntimeError(
            f"runtime_env['uv'] could not publish the built environment "
            f"to {dest} and no concurrent build published one either — "
            "is the temp directory being cleaned concurrently?")

    def peer_ready() -> Optional[str]:
        """A peer's finished venv, if one was published while we failed."""
        if os.path.exists(os.path.join(dest, ".ready")):
            import shutil

            shutil.rmtree(staging, ignore_errors=True)
            return site_dir(dest)
        return None

    try:
        subprocess.run(["uv", "venv", "--quiet", staging], check=True,
                       capture_output=True, text=True, timeout=120)
        install = ["uv", "pip", "install", "--quiet",
                   "--python", os.path.join(staging, "bin", "python"),
                   "--offline"]
        if find_links:
            install += ["--find-links", find_links]
        install += list(packages)
        p = subprocess.run(install, capture_output=True, text=True,
                           timeout=600)
        if p.returncode != 0:
            # offline resolution failed: accept a peer's finished venv, or
            # the baked image IF it already satisfies the pins, else
            # surface both failures
            peer = peer_ready()
            if peer is not None:
                return peer
            try:
                check_pip_requirements(packages)
            except RuntimeError as image_err:
                peer = peer_ready()  # a peer may have published meanwhile
                if peer is not None:
                    return peer
                raise RuntimeError(
                    "runtime_env['uv'] could not build the environment: uv "
                    f"failed ({(p.stderr or p.stdout).strip()[-400:]}) and "
                    f"the immutable image does not satisfy the pins "
                    f"({image_err}). Provide a wheel directory via "
                    "find_links / RAY_TPU_UV_FIND_LINKS, or bake the "
                    "packages into the image.") from None
            # cache the negative so the rest of the pool skips the doomed
            # venv+install at bootstrap
            if publish(".validate_only"):
                return ""
            return published_result()
        if publish(".ready"):
            return site_dir(dest)
        return published_result()
    except subprocess.CalledProcessError as e:
        import shutil

        peer = peer_ready()
        if peer is not None:
            return peer
        shutil.rmtree(staging, ignore_errors=True)
        raise RuntimeError(
            "runtime_env['uv'] venv creation failed: "
            f"{(e.stderr or e.stdout or str(e)).strip()[-400:]}") from None
    except (subprocess.TimeoutExpired, FileNotFoundError) as e:
        import shutil

        peer = peer_ready()
        if peer is not None:
            return peer
        shutil.rmtree(staging, ignore_errors=True)
        raise RuntimeError(
            f"runtime_env['uv'] setup failed: {e} — is uv on PATH?"
        ) from None


def env_hash(runtime_env: Optional[dict]) -> str:
    """Stable content hash; '' = the default (env-less) worker pool."""
    if not runtime_env:
        return ""
    blob = json.dumps(runtime_env, sort_keys=True, default=str).encode()
    return hashlib.sha1(blob).hexdigest()[:16]


def path_fingerprint(path: str) -> str:
    """Cheap content fingerprint (relpath, size, mtime_ns per file) — the
    driver's cache key for packaged local dirs; avoids re-zipping unchanged
    trees on every submission while still catching edits."""
    h = hashlib.sha1()
    if os.path.isfile(path):
        st = os.stat(path)
        h.update(f"{os.path.basename(path)}:{st.st_size}:{st.st_mtime_ns}".encode())
    else:
        for root, dirs, files in os.walk(path):
            dirs.sort()
            for fname in sorted(files):
                full = os.path.join(root, fname)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                rel = os.path.relpath(full, path)
                h.update(f"{rel}:{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()[:16]


def _zip_path(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            base = os.path.basename(os.path.normpath(path))
            for root, _, files in os.walk(path):
                for fname in files:
                    full = os.path.join(root, fname)
                    rel = os.path.join(base, os.path.relpath(full, path))
                    zf.write(full, rel)
    return buf.getvalue()


def package(worker, runtime_env: Optional[dict]) -> Optional[dict]:
    """Driver-side: upload local py_modules/working_dir to the GCS KV and
    rewrite the env to content-addressed URIs (reference: uri_cache.py)."""
    runtime_env = normalize(runtime_env)
    if runtime_env is None:
        return None
    out = dict(runtime_env)

    def upload(path: str) -> str:
        data = _zip_path(path)
        sha = hashlib.sha1(data).hexdigest()[:16]
        key = f"pymod:{sha}"
        if not worker.gcs.call("KVExists", {"key": key}):
            worker.gcs.call("KVPut", {"key": key, "value": data})
        return f"{_KV_PREFIX}{key}"

    if "py_modules" in out:
        mods = []
        for m in out["py_modules"]:
            mods.append(upload(m) if not str(m).startswith(_KV_PREFIX) else m)
        out["py_modules"] = mods
    wd = out.get("working_dir")
    if wd and not str(wd).startswith(_KV_PREFIX):
        out["working_dir"] = upload(wd)
    hook = out.get("worker_process_setup_hook")
    if callable(hook):
        # Ship the callable through the function registry (the same fn:<sha>
        # KV namespace task functions use), so the spawned worker fetches it
        # once and the env stays a JSON-serializable pool key (the raylet
        # hashes it and exports it via RAY_TPU_RUNTIME_ENV).
        out["worker_process_setup_hook"] = {
            "kv": _KV_PREFIX + publish_setup_hook(worker, hook)}
    return out


def publish_setup_hook(worker, hook) -> str:
    """Serialize + publish a setup-hook callable; returns its fn:<sha> key."""
    from ray_tpu._private import serialization

    blob = serialization.dumps_inline(hook)
    key = f"fn:{hashlib.sha1(blob).hexdigest()}"
    if not worker.gcs.call("KVExists", {"key": key}):
        worker.gcs.call("KVPut", {"key": key, "value": blob})
    return key


def _materialize(gcs_client, uri: str) -> str:
    """Fetch kv://pymod:<sha> into a cached extract dir; returns the dir.
    Concurrent workers race safely: extract to a private temp dir, then
    publish with one atomic rename (first one wins)."""
    key = uri[len(_KV_PREFIX):]
    base = os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env")
    dest = os.path.join(base, key.replace(":", "_"))
    if os.path.exists(dest):
        return dest
    data = gcs_client.call("KVGet", {"key": key})
    if data is None:
        raise RuntimeError(f"runtime_env package {uri} not found in GCS KV")
    os.makedirs(base, exist_ok=True)
    staging = tempfile.mkdtemp(prefix=".staging-", dir=base)
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(staging)
    try:
        os.rename(staging, dest)
    except OSError:  # another worker published first; use theirs
        import shutil

        shutil.rmtree(staging, ignore_errors=True)
    return dest


def apply_in_worker(gcs_client, runtime_env: Optional[dict]):
    """Worker bootstrap: export env_vars, materialize packages, set paths.
    Runs once per (dedicated) worker process before user code."""
    if not runtime_env:
        return
    if runtime_env.get("pip"):
        check_pip_requirements(runtime_env["pip"])
    for name, value in (runtime_env.get("env_vars") or {}).items():
        os.environ[name] = str(value)
    if runtime_env.get("uv"):
        site = materialize_uv_env(runtime_env["uv"])
        if site:
            # in-process activation: the venv's site-packages shadows the
            # baked image for this dedicated worker (workers fork off the
            # zygote, so re-exec'ing into the venv python would forfeit
            # the warm start; path-precedence activation is how .pth-based
            # virtualenv activation works anyway).  Runs AFTER env_vars so
            # a user-supplied PYTHONPATH is merged behind the venv, not
            # clobbering it.
            sys.path.insert(0, site)
            os.environ["PYTHONPATH"] = (
                site + os.pathsep + os.environ.get("PYTHONPATH", ""))
    for uri in runtime_env.get("py_modules") or ():
        # a py_module dir is importable by its basename (reference semantics)
        root = _materialize(gcs_client, uri)
        if root not in sys.path:
            sys.path.insert(0, root)
    wd = runtime_env.get("working_dir")
    if wd:
        root = _materialize(gcs_client, wd)
        entries = os.listdir(root)
        target = (os.path.join(root, entries[0])
                  if len(entries) == 1 and os.path.isdir(os.path.join(root, entries[0]))
                  else root)
        sys.path.insert(0, target)
        os.chdir(target)
    hook = runtime_env.get("worker_process_setup_hook")
    if hook:
        # Runs ONCE per worker process, after every other env field is in
        # place (env_vars exported, py_modules/working_dir on sys.path) and
        # BEFORE the worker registers for its first task (reference:
        # ray.init(runtime_env={"worker_process_setup_hook": fn}) —
        # _private/runtime_env/setup_hook.py ships the callable via the
        # function manager).  A raising hook fails worker setup loudly, so
        # leases surface the error instead of running half-configured.
        from ray_tpu._private import serialization

        if callable(hook):
            fn = hook  # same-process application (driver-mode envs, tests)
        else:
            uri = hook["kv"] if isinstance(hook, dict) else hook
            blob = gcs_client.call("KVGet", {"key": uri[len(_KV_PREFIX):]})
            if blob is None:
                raise RuntimeError(
                    f"worker_process_setup_hook {uri} not found in GCS KV")
            fn = serialization.loads_inline(blob)
        fn()
