"""Simulated mega-cluster harness: hundreds–thousands of skeleton raylets
against ONE real GCS, in one process, with no sockets and no threads per
node.

The scaling questions this answers ("is sync traffic proportional to churn
or to cluster size?", "how many publishes does one control event cost the
GCS?") are protocol properties, not kernel properties — so the harness
keeps the real ``GcsServer`` (real handlers, real versioned changelog,
real ``Pubsub`` tree logic) and replaces only what cannot exist 1000x in
one process:

- **SkeletonRaylet** — the report loop + view application of a raylet and
  nothing else (no worker pool, no object store, no threads; the chaos-
  injection style of ``tests/test_preemption.py``).  View application goes
  through the SAME ``cluster_view.apply_sync_reply`` protocol code the
  production raylet runs, over a plain-dict store.
- **SimNet** — an in-process ClientPool lookalike routing the pubsub
  plane's ``call_async``/``call_async_frame`` to skeleton handlers
  synchronously, raising ``ConnectionLost`` for killed nodes exactly like
  a refused connect.  Ticks are driven explicitly by the caller
  (injectable-clock style: convergence is measured in tick rounds, never
  wall time), so the harness is deterministic and leaves no threads behind
  beyond the one real GCS's own loops.

Metering rides the production metric families
(``ray_tpu_gcs_sync_bytes_total{kind}``,
``ray_tpu_pubsub_relay_publishes_total{role}``,
``ray_tpu_gcs_sync_version``) — the same counters the perf-smoke gate and
bench.py's ``control_plane`` section read.
"""

from __future__ import annotations

import pickle
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import runtime_metrics
from ray_tpu._private.cluster_view import (
    DictViewStore,
    apply_sync_reply,
    tree_partition,
)
from ray_tpu._private.config import RayTpuConfig
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.ids import NodeID
from ray_tpu._private.rpc import ConnectionLost, decode_body

Addr = Tuple[str, int]


class _SimClient:
    """One fake-address endpoint of a SimNet (RpcClient lookalike)."""

    def __init__(self, net: "SimNet", address: Addr):
        self._net = net
        self.address = address

    def call_async(self, method: str, payload=None) -> Future:
        target = self._net.registry.get(self.address)
        if target is None:
            # same surface as a refused connect on a real RpcClient
            raise ConnectionLost(f"cannot connect to {self.address}")
        self._net.sends[method] = self._net.sends.get(method, 0) + 1
        fut: Future = Future()
        if self._net.drop_relay_publishes and method == "RelayPublish":
            fut.set_result(True)  # counted, not delivered (bulk build-up)
            return fut
        try:
            fut.set_result(getattr(target, f"Handle{method}")(payload))
        except ConnectionLost:
            raise
        except Exception as e:  # noqa: BLE001 — handler error, peer alive
            fut.set_exception(e)
        return fut

    def call_async_frame(self, parts) -> Future:
        body = bytearray(b"".join(bytes(p) for p in parts))
        method, payload = decode_body(body)
        return self.call_async(method, payload)

    def call(self, method: str, payload=None, timeout=None, **_kw):
        return self.call_async(method, payload).result()

    def notify(self, method: str, payload=None):
        try:
            self.call_async(method, payload)
        except ConnectionLost:
            pass


class SimNet:
    """In-process 'network': fake addresses -> handler objects."""

    def __init__(self):
        self.registry: Dict[Addr, object] = {}
        self.sends: Dict[str, int] = {}      # method -> total sends
        self.drop_relay_publishes = False
        self._clients: Dict[Addr, _SimClient] = {}

    def get(self, address) -> _SimClient:
        address = tuple(address)
        cli = self._clients.get(address)
        if cli is None:
            cli = self._clients[address] = _SimClient(self, address)
        return cli

    def invalidate(self, address):
        self._clients.pop(tuple(address), None)

    def close_all(self):
        self._clients.clear()


class SkeletonRaylet:
    """Report loop + view application only — no worker pool, no object
    store, no threads.  ``tick()`` is one resource-report round trip; view
    application is the shared ``cluster_view`` protocol over a dict."""

    def __init__(self, gcs: GcsServer, net: SimNet, index: int,
                 resources: Optional[Dict[str, float]] = None):
        self.gcs = gcs
        self.net = net
        self.node_id = NodeID.random()
        self.address: Addr = ("sim-raylet", index)
        self.resources = dict(resources or {"CPU": 1.0})
        self.available = dict(self.resources)
        self.view: Dict[NodeID, dict] = {}
        self._store = DictViewStore(self.view)
        self.view_version = -1
        self.alive = True
        self.restarts = 0
        self.events_seen: List[dict] = []
        self.relay_sends = 0
        net.registry[self.address] = self

    # -- sync plane -------------------------------------------------------

    def register(self):
        reply = self.gcs.HandleRegisterNode({
            "node_id": self.node_id, "address": self.address,
            "resources": dict(self.resources), "labels": {},
            "is_head": False,
        })
        self._apply(reply)
        return reply

    def tick(self, force_full: bool = False, apply_reply: bool = True):
        """One report tick.  ``force_full`` asks for a whole snapshot every
        time (known_version=-1) — the pre-delta behavior, kept as the A/B
        baseline.  ``apply_reply=False`` simulates a dropped reply: the
        GCS saw the report but this raylet learned nothing."""
        known = -1 if force_full else self.view_version
        reply = self.gcs.HandleReportResources({
            "node_id": self.node_id, "available": dict(self.available),
            "known_version": known,
        })
        if reply.get("restart"):
            self.restarts += 1
            self.register()
            return reply
        if apply_reply:
            self._apply(reply)
        return reply

    def _apply(self, reply):
        self.view_version = apply_sync_reply(
            reply, self._store, self.node_id, self.view_version)

    # -- relay plane (mirrors Raylet.HandleRelayPublish) ------------------

    def HandleRelayPublish(self, req):
        frame = req.get("frame")
        if not isinstance(frame, (bytes, bytearray)):
            frame = bytes(frame)
        subtree = [tuple(a) for a in (req.get("subtree") or ())]
        if subtree:
            self._relay_forward(frame, subtree)
        self.events_seen.append(pickle.loads(frame))
        return True

    def _relay_forward(self, frame: bytes, subtree: List[Addr]):
        # same tree shape as Raylet._relay_forward (via the shared
        # tree_partition), but synchronous: SimNet surfaces dead peers as
        # an immediate ConnectionLost, so the production forwarder's
        # async done-callback fallback leg has no sim equivalent — the
        # real-socket leg is covered by
        # tests/test_control_plane.py::test_real_raylets_delta_sync_and_relay_plane
        fanout = self.gcs.config.pubsub_tree_fanout
        for group in tree_partition(subtree, fanout):
            head, rest = group[0], group[1:]
            try:
                self.net.get(head).call_async(
                    "RelayPublish", {"frame": frame, "subtree": rest})
            except ConnectionLost:
                # dead child: deliver its subtree directly (same fallback
                # the production relay applies; like production, only
                # sends that went out are counted)
                for t in rest:
                    try:
                        self.net.get(t).call_async(
                            "RelayPublish", {"frame": frame, "subtree": []})
                    except ConnectionLost:
                        continue
                    runtime_metrics.inc_relay_publish("fallback")
                continue
            self.relay_sends += 1
            runtime_metrics.inc_relay_publish("relay")


class MegaClusterHarness:
    """One real GCS + N skeleton raylets, ticked explicitly.

    Typical session::

        h = MegaClusterHarness(num_nodes=1000)
        h.build()                       # register everyone
        h.tick_all()                    # settle to the current version
        stats = h.tick_all(rounds=5)    # steady state: empty deltas
        h.drain_node(h.skeletons[3]); h.kill_node(h.skeletons[7])
        lag = h.converge()              # tick rounds until views match
        h.close()
    """

    def __init__(self, num_nodes: int,
                 fanout: Optional[int] = None,
                 changelog_len: Optional[int] = None,
                 resources: Optional[Dict[str, float]] = None):
        cfg = RayTpuConfig()
        # ticks are driven manually — the wall-clock health sweep must
        # never declare a paused simulation dead
        cfg.health_check_failure_threshold = 1_000_000_000
        cfg.heartbeat_interval_s = 3600.0
        if fanout is not None:
            cfg.pubsub_tree_fanout = fanout
        if changelog_len is not None:
            cfg.cluster_view_changelog_len = changelog_len
        self.net = SimNet()
        self.gcs = GcsServer(config=cfg)
        # route the pubsub plane through the in-process network (relay
        # targets carry sim addresses only this net can reach)
        self.gcs.pubsub._pool = self.net
        self._probe_seq = 0
        self.skeletons: List[SkeletonRaylet] = [
            SkeletonRaylet(self.gcs, self.net, i, resources)
            for i in range(num_nodes)
        ]

    # -- lifecycle --------------------------------------------------------

    def build(self):
        """Register every skeleton.  Relay deliveries are suppressed (but
        still counted) during the storm — 1000 registrations each fanning
        a NODE-alive event to every earlier node is O(N^2) deliveries the
        scaling measurements don't need."""
        self.net.drop_relay_publishes = True
        try:
            for s in self.skeletons:
                s.register()
        finally:
            self.net.drop_relay_publishes = False

    def close(self):
        self.gcs.shutdown()
        self.net.registry.clear()
        self.net.close_all()

    # -- ticking + metering ----------------------------------------------

    def alive_skeletons(self) -> List[SkeletonRaylet]:
        return [s for s in self.skeletons if s.alive]

    def tick_all(self, rounds: int = 1, force_full: bool = False) -> dict:
        """Drive ``rounds`` full report rounds; returns the metered cost:
        sync bytes by kind (off the production counters) and GCS handler
        wall time, totalled over every tick."""
        before = runtime_metrics.sync_snapshot()
        handler_s = 0.0
        ticks = 0
        for _ in range(rounds):
            for s in self.alive_skeletons():
                t0 = time.perf_counter()
                s.tick(force_full=force_full)
                handler_s += time.perf_counter() - t0
                ticks += 1
        after = runtime_metrics.sync_snapshot()
        return {
            "ticks": ticks,
            "gcs_handler_s": handler_s,
            "delta_bytes": after["delta_bytes"] - before["delta_bytes"],
            "full_bytes": after["full_bytes"] - before["full_bytes"],
        }

    # -- churn ------------------------------------------------------------

    def add_nodes(self, n: int) -> List[SkeletonRaylet]:
        added = []
        for i in range(n):
            s = SkeletonRaylet(self.gcs, self.net,
                               len(self.skeletons) + i, None)
            s.register()
            added.append(s)
        self.skeletons.extend(added)
        return added

    def drain_node(self, s: SkeletonRaylet, reason: str = "sim drain"):
        self.gcs.HandleDrainNode({"node_id": s.node_id, "reason": reason})

    def kill_node(self, s: SkeletonRaylet, reason: str = "sim kill",
                  notify_gcs: bool = True):
        """Crash a node: unreachable immediately; the GCS hears about it
        only when ``notify_gcs`` (else it keeps publishing through/to the
        corpse — the dead-relay fallback scenario)."""
        s.alive = False
        self.net.registry.pop(s.address, None)
        if notify_gcs:
            self.gcs.HandleNodeDead({"node_id": s.node_id, "reason": reason})

    # -- convergence ------------------------------------------------------

    def gcs_states(self) -> Dict[NodeID, str]:
        with self.gcs._lock:
            return {nid: snap["state"]
                    for nid, snap in self.gcs._node_snaps.items()}

    def diverged(self) -> List[tuple]:
        """(skeleton_index, why) for every live skeleton whose applied view
        disagrees with the GCS's — empty means converged."""
        expect = self.gcs_states()
        bad = []
        for i, s in enumerate(self.skeletons):
            if not s.alive:
                continue
            want = {nid: st for nid, st in expect.items()
                    if nid != s.node_id}
            if set(s.view) != set(want):
                bad.append((i, "node-set mismatch"))
                continue
            for nid, st in want.items():
                if s.view[nid]["state"] != st:
                    bad.append((i, f"state mismatch on {nid}"))
                    break
        return bad

    def converge(self, max_rounds: int = 10) -> int:
        """Tick until every live skeleton's view matches the GCS view;
        returns the number of rounds taken (the convergence lag)."""
        for rounds in range(1, max_rounds + 1):
            self.tick_all()
            if not self.diverged():
                return rounds
        raise AssertionError(
            f"views did not converge within {max_rounds} rounds: "
            f"{self.diverged()[:5]}")

    # -- pubsub A/B -------------------------------------------------------

    def publish_probe(self) -> dict:
        """Publish one control event through the NODE channel and return
        {root_sends, relay_sends, fallback_sends, delivered}: the GCS-side
        fan-out cost (root) vs what the relay tree carried, plus how many
        live skeletons actually received it."""
        self._probe_seq += 1
        seq = self._probe_seq
        before = runtime_metrics.sync_snapshot()["relay_publishes"]
        self.gcs.pubsub.publish(
            "NODE", {"event": "sim-probe", "node_id": None, "seq": seq})
        after = runtime_metrics.sync_snapshot()["relay_publishes"]
        delivered = sum(
            1 for s in self.skeletons if s.alive
            and any(e.get("message", {}).get("seq") == seq
                    for e in s.events_seen))
        return {
            "root_sends": after.get("root", 0) - before.get("root", 0),
            "relay_sends": after.get("relay", 0) - before.get("relay", 0),
            "fallback_sends": (after.get("fallback", 0)
                               - before.get("fallback", 0)),
            "delivered": delivered,
        }
