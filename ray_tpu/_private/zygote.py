"""Zygote (pre-fork) worker spawner.

Worker spawn via ``Popen([sys.executable, -m, workers_main])`` pays full
interpreter + import startup per worker — measured 2.3 s on this image
(the TPU-tunnel sitecustomize imports jax into EVERY python process).
The zygote is one warm process that performs those imports ONCE and then
``fork()``s a child per spawn request: child startup is ~50 ms, and an
actor/worker fan-out of hundreds becomes seconds instead of tens of
minutes.  (Same design as Android's app zygote and the reference's
prestarted-worker pool, worker_pool.cc — taken further because process
creation itself is the bottleneck here.)

Fork safety: the zygote stays SINGLE-THREADED for its whole life (one
accept loop, no executors), so no lock can be held at fork time.  jax is
imported but never used in the zygote — the backend factory registered by
the sitecustomize stays inert (no client, no sockets, no threads) until a
CHILD first touches jax.  Children get a fresh session (setsid), their
own log file on fd 1/2, a rebuilt ``os.environ``, and run the normal
``workers_main.main()`` — registration with the raylet is unchanged.

Zombie reaping: children are the zygote's children, so the zygote reaps
them with a SIGCHLD handler; the raylet's liveness checks
(``_PidHandle.poll`` → ``kill(pid, 0)``) then see death promptly.

Protocol (unix socket, one JSON line per connection):
  request:  {"env": {...}, "log_file": "/path", "deadline": unix_ts}
            |  {"shutdown": true}
  reply:    {"pid": 1234}  |  {"error": "..."}
``deadline`` (optional) is the wall-clock instant the CLIENT stops
waiting; the zygote drops requests already past it instead of forking a
worker nobody tracks (the client has Popen-fallen-back by then).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import time


def _recv_line(conn: socket.socket) -> bytes:
    buf = b""
    while not buf.endswith(b"\n"):
        try:
            chunk = conn.recv(65536)
        except OSError:
            break
        if not chunk:
            break
        buf += chunk
    return buf


def _reply(conn: socket.socket, obj) -> bool:
    """Best-effort reply: a client that already hung up (spawn timeout)
    must never take the zygote loop down with BrokenPipeError.  Returns
    whether the reply was delivered — the fork path kills the child when it
    wasn't, since an unannounced pid would become an untracked duplicate of
    the client's Popen fallback."""
    try:
        conn.sendall(json.dumps(obj).encode() + b"\n")
        return True
    except OSError:
        return False
    finally:
        try:
            conn.close()
        except OSError:
            pass


def serve(sock_path: str) -> None:
    """Zygote main loop (runs as ``python -m ray_tpu._private.zygote``)."""
    # Pre-warm: everything a worker imports before it can serve a task.
    # These are the expensive imports the fork amortizes.
    import ray_tpu  # noqa: F401
    import ray_tpu._private.worker  # noqa: F401
    from ray_tpu._private import workers_main

    try:
        # compile the native stack-dump component once here: children then
        # dlopen the cached .so instead of each paying a g++ build
        from ray_tpu import _native

        _native.load("stack_dump")
    except Exception:  # noqa: BLE001 — warm-cache build is an optimization only
        pass

    def _reap(_sig, _frm):
        while True:
            try:
                pid, _ = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return

    signal.signal(signal.SIGCHLD, _reap)

    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv.bind(sock_path)
    srv.listen(64)
    # readiness handshake: the raylet waits for this line
    sys.stdout.write("ZYGOTE_READY\n")
    sys.stdout.flush()

    while True:
        try:
            conn, _ = srv.accept()
        except InterruptedError:
            continue
        try:
            req = json.loads(_recv_line(conn) or b"null")
        except ValueError:
            req = None
        if not isinstance(req, dict) or (
                not req.get("shutdown") and "log_file" not in req):
            # client died mid-send (timeout/close): never fork on junk
            conn.close()
            continue
        if req.get("shutdown"):
            conn.close()
            break
        # stale-request guard: the client stops waiting at its (short)
        # socket deadline and Popen-falls-back; forking anyway would add an
        # untracked duplicate worker.  Same-host wall clock, so the
        # comparison is skew-free.
        deadline = req.get("deadline")
        if deadline is not None and time.time() > deadline:
            conn.close()
            continue
        try:
            pid = os.fork()
        except OSError as e:
            _reply(conn, {"error": str(e)})
            continue
        if pid == 0:
            # ---- child: becomes a normal worker process ----
            try:
                srv.close()
                conn.close()
                os.setsid()
                lf = os.open(req["log_file"],
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                os.dup2(lf, 1)
                os.dup2(lf, 2)
                os.close(lf)
                os.environ.clear()
                os.environ.update(req["env"])
                signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                workers_main.main()
                os._exit(0)
            except BaseException:  # noqa: BLE001 — never unwind into the loop
                import traceback

                traceback.print_exc()
                os._exit(1)
        if not _reply(conn, {"pid": pid}):
            # the raylet gave up on this request (short spawn timeout) and
            # already took the Popen path: reap the orphan before it can
            # register as an untracked extra worker
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    srv.close()
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass


class ZygoteClient:
    """Raylet-side handle: starts the zygote in the BACKGROUND, requests
    forks once it is ready.

    ``spawn`` never blocks on zygote startup — while the zygote warms (or
    after it dies, while a restart is in flight) it returns ``None`` and
    the caller uses the Popen fallback, so the zygote is a pure fast path
    and a wedged zygote can never stall the raylet's dispatch loop (which
    calls spawn under its lock)."""

    def __init__(self, state_dir: str, worker_env: dict, log_sink):
        from ray_tpu._private.analysis.lock_witness import make_lock

        self._sock_path = os.path.join(
            state_dir, f"zygote-{os.getpid()}.sock")
        self._env = worker_env
        self._log_sink = log_sink  # file path for the zygote's own output
        self._proc = None
        self._lock = make_lock("ZygoteClient._lock")
        self._starting = False
        self._stopped = False
        self.start_async()

    def start_async(self):
        """Kick off (re)start in a daemon thread; returns immediately."""
        import threading

        if sys.platform != "linux":
            return
        with self._lock:
            if self._stopped or self._starting:
                return
            if self._proc is not None and self._proc.poll() is None:
                return
            self._starting = True
        threading.Thread(target=self._start, daemon=True,
                         name="zygote-start").start()

    def _start(self):
        import subprocess
        import time

        try:
            try:
                os.unlink(self._sock_path)
            except FileNotFoundError:
                pass
            lf = open(self._log_sink, "ab")
            proc = subprocess.Popen(
                [sys.executable, "-u", "-m", "ray_tpu._private.zygote",
                 self._sock_path],
                env=self._env, stdout=lf, stderr=subprocess.STDOUT)
            lf.close()
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if os.path.exists(self._sock_path):
                    break
                if proc.poll() is not None:
                    return
                time.sleep(0.01)
            with self._lock:
                if self._stopped:
                    proc.terminate()
                else:
                    self._proc = proc
        except Exception:  # noqa: BLE001 — boot failure falls back to Popen spawns (counted there)
            pass
        finally:
            with self._lock:
                self._starting = False

    def spawn(self, env: dict, log_file: str):
        """Fork one worker; returns its pid, or None to use the fallback
        (zygote still warming, dead, or wedged).

        The socket budget is SHORT (zygote_spawn_timeout_s, default 2 s):
        this runs under the raylet's dispatch lock, so a wedged-but-alive
        zygote must cost at most one short timeout before the Popen path
        takes over — never the 15 s a generous timeout allowed.  Fallbacks
        are counted (ray_tpu_raylet_zygote_fallback_total) so a sick zygote
        is visible instead of silently degrading every spawn to ~2.3 s."""
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            self.start_async()  # warm it for next time
            return None
        conn = None
        try:
            from ray_tpu._private.config import global_config

            budget = max(global_config().zygote_spawn_timeout_s, 0.1)
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(budget)
            conn.connect(self._sock_path)
            # deadline rides the request: once we stop waiting, the zygote
            # must NOT fork a duplicate of the Popen fallback (and a fork
            # whose reply can't be delivered is killed zygote-side)
            conn.sendall(json.dumps(
                {"env": env, "log_file": log_file,
                 "deadline": time.time() + budget}).encode() + b"\n")
            reply = json.loads(_recv_line(conn) or b"{}")
            pid = reply.get("pid")
            if pid is None:
                self._note_fallback()
            return pid
        except Exception:  # noqa: BLE001
            self._note_fallback()
            return None
        finally:
            # deterministic close: the zygote detects an abandoned request
            # by its reply send failing, so the socket must die NOW, not at
            # a later GC
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    @staticmethod
    def _note_fallback():
        try:
            from ray_tpu._private import runtime_metrics

            runtime_metrics.inc_zygote_fallback()
        except Exception:  # noqa: BLE001 — fallback counter is telemetry; never block a spawn
            pass

    def shutdown(self):
        with self._lock:
            self._stopped = True  # an in-flight _start will self-terminate
            proc, self._proc = self._proc, None
        if proc is None:
            return
        try:
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            conn.settimeout(2.0)
            conn.connect(self._sock_path)
            conn.sendall(b'{"shutdown": true}\n')
            conn.close()
        except Exception:  # noqa: BLE001 — zygote already dead: terminate below still runs
            pass
        try:
            proc.terminate()
        except Exception:  # noqa: BLE001 — already-exited zygote is the desired state
            pass


if __name__ == "__main__":
    serve(sys.argv[1])
