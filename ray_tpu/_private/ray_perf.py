"""Core-runtime microbenchmarks.

reference: python/ray/_private/ray_perf.py:122-290 — the named
microbenchmark suite ("single client get calls", "1:1 actor calls sync",
"n:n async actor calls", put/get throughput) run per release by
release/microbenchmark/run_microbenchmark.py.

Run: ``python -m ray_tpu._private.ray_perf [--fast]``
Prints one line per benchmark: name, ops/s.  ``main(fast=True)`` trims
iteration counts for CI smoke use.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List


def timeit(name: str, fn: Callable[[], None], multiplier: int = 1,
           *, min_time_s: float = 1.0, fast: bool = False) -> Dict[str, float]:
    """Run fn repeatedly for ~min_time_s; report ops/s (reference:
    ray_perf.py timeit)."""
    if fast:
        min_time_s = 0.2
    fn()  # warmup
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time_s:
        fn()
        count += 1
    elapsed = time.perf_counter() - start
    rate = count * multiplier / elapsed
    print(f"{name:<45s} {rate:>12.1f} ops/s")
    return {"name": name, "ops_per_s": rate}


def main(fast: bool = False) -> List[Dict[str, float]]:
    import numpy as np

    import ray_tpu

    results = []
    ray_tpu.init(num_cpus=4)
    try:
        # -- puts/gets ---------------------------------------------------
        small = b"x" * 1024

        def put_small():
            ray_tpu.put(small)

        results.append(timeit("single client put (1KB, in-band)", put_small,
                              fast=fast))

        big = np.zeros(1 << 20, dtype=np.uint8)

        def put_get_big():
            ray_tpu.get(ray_tpu.put(big))

        results.append(timeit("single client put+get (1MB, plasma)",
                              put_get_big, fast=fast))

        ref_cached = ray_tpu.put(big)

        def get_big():
            ray_tpu.get(ref_cached)

        results.append(timeit("single client get (1MB, plasma hit)", get_big,
                              fast=fast))

        # -- tasks -------------------------------------------------------
        @ray_tpu.remote
        def tiny():
            return b"ok"

        def batch_tasks():
            ray_tpu.get([tiny.remote() for _ in range(20)])

        results.append(timeit("task submit+get (batch 20)", batch_tasks,
                              multiplier=20, fast=fast))

        # -- lease fast path A/B (ISSUE 5) -------------------------------
        # same workload with the owner-side lease cache on vs off; the
        # delta is what lease reuse + pipelining + batched grants buy
        from ray_tpu._private.config import global_config
        from ray_tpu._private.worker import get_global_worker

        def batch_100():
            ray_tpu.get([tiny.remote() for _ in range(100)])

        results.append(timeit("tasks/s (lease reuse on, batch 100)",
                              batch_100, multiplier=100, fast=fast))

        cfg = global_config()
        cfg.worker_lease_reuse_enabled = False
        get_global_worker()._submitter.release_all_leases()
        try:
            results.append(timeit("tasks/s (lease reuse off, batch 100)",
                                  batch_100, multiplier=100, fast=fast))
        finally:
            cfg.worker_lease_reuse_enabled = True

        # single-worker pipelining: every task binds to ONE leased worker
        # (CPU:4 on the 4-CPU bench cluster), so depth comes purely from
        # max_tasks_in_flight_per_worker
        @ray_tpu.remote(num_cpus=4)
        def tiny4():
            return b"ok"

        def pipelined_tasks():
            ray_tpu.get([tiny4.remote() for _ in range(20)])

        ray_tpu.get(tiny4.remote())  # spawn + warm the single lease
        results.append(timeit("1:1 pipelined submission (batch 20)",
                              pipelined_tasks, multiplier=20, fast=fast))

        # -- actors ------------------------------------------------------
        @ray_tpu.remote
        class Echo:
            def ping(self, x=None):
                return x

        # fractional CPUs so the 1 + 4 actors fit the 4-CPU bench cluster
        Echo = Echo.options(num_cpus=0.5)
        actor = Echo.remote()
        ray_tpu.get(actor.ping.remote())

        def sync_call():
            ray_tpu.get(actor.ping.remote())

        results.append(timeit("1:1 actor calls sync", sync_call, fast=fast))

        def pipelined_calls():
            ray_tpu.get([actor.ping.remote() for _ in range(20)])

        results.append(timeit("1:1 actor calls async (pipeline 20)",
                              pipelined_calls, multiplier=20, fast=fast))

        actors = [Echo.remote() for _ in range(4)]
        ray_tpu.get([a.ping.remote() for a in actors])

        def fan_out():
            ray_tpu.get([a.ping.remote() for a in actors for _ in range(5)])

        results.append(timeit("n:n actor calls (4 actors, pipeline 5)",
                              fan_out, multiplier=20, fast=fast))
    finally:
        ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    import sys

    main(fast="--fast" in sys.argv)
