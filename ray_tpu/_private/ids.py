"""Unique identifiers for cluster entities.

TPU-native rebuild of the reference ID scheme (reference: src/ray/common/id.h).
The reference derives task/object IDs deterministically from parent task + index
so that lineage reconstruction can re-create the *same* object IDs when a task
is re-executed.  We keep that property: an ObjectID is
``sha1(task_id || return_index)`` and a re-submitted task reuses its TaskID, so
reconstructed objects keep their identity.
"""

from __future__ import annotations

import hashlib
import os
import random as _random
import threading

_NIL = "0" * 32

# Per-process CSPRNG-seeded generator for id entropy.  os.urandom (and even
# getpid) per id is a syscall costing ~100 µs on some kernels (measured on
# the task-submit hot path); one urandom seed per process keeps ids unique
# across processes (pid + 256-bit seed) at ~1 µs per id.  Re-seeded on fork
# (register_at_fork) so zygote-forked workers never share a stream.
_rng_lock = threading.Lock()
_rng_state: list = [None]


def _reseed_rng():
    _rng_state[0] = _random.Random(
        os.urandom(32) + os.getpid().to_bytes(4, "little"))


_reseed_rng()
os.register_at_fork(after_in_child=_reseed_rng)


def _rand_hex(nchars: int) -> str:
    with _rng_lock:
        return "%0*x" % (nchars, _rng_state[0].getrandbits(nchars * 4))


class BaseID:
    """Hex-string backed ID. Cheap, hashable, picklable."""

    __slots__ = ("_hex",)
    _length = 32  # hex chars

    def __init__(self, hex_str: str):
        self._hex = hex_str

    @classmethod
    def random(cls) -> "BaseID":
        return cls(_rand_hex(cls._length))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls("0" * cls._length)

    def is_nil(self) -> bool:
        return self._hex == "0" * self._length

    def hex(self) -> str:
        return self._hex

    def __hash__(self):
        return hash(self._hex)

    def __eq__(self, other):
        return isinstance(other, BaseID) and self._hex == other._hex

    def __lt__(self, other):
        return self._hex < other._hex

    def __repr__(self):
        return f"{type(self).__name__}({self._hex[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._hex,))


class JobID(BaseID):
    _length = 8


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    _length = 24


class PlacementGroupID(BaseID):
    _length = 24


class TaskID(BaseID):
    _length = 32

    @classmethod
    def for_attempt(cls, base: "TaskID", attempt: int) -> "TaskID":
        """Same task identity across attempts; attempt carried separately."""
        return base


class ObjectID(BaseID):
    _length = 40

    @classmethod
    def from_task(cls, task_id: TaskID, return_index: int) -> "ObjectID":
        h = hashlib.sha1(f"{task_id.hex()}:{return_index}".encode()).hexdigest()
        return cls(h)

    @classmethod
    def from_put(cls, worker_id: WorkerID, put_index: int) -> "ObjectID":
        h = hashlib.sha1(f"put:{worker_id.hex()}:{put_index}".encode()).hexdigest()
        return cls(h)


class _Counter:
    def __init__(self):
        self._n = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._n += 1
            return self._n
