"""Resource accounting primitives.

TPU-native rebuild of the reference's scheduling data model
(reference: src/ray/common/scheduling/resource_set.h:33 ResourceSet,
:143 NodeResourceSet, scheduling_ids.h:33-44 predefined resources,
fixed_point.h FixedPoint arithmetic).

Quantities are stored as integers in units of 1/10000 (the reference's
FixedPoint uses the same resolution) so fractional resources never drift.
``TPU`` is a predefined resource alongside CPU/memory — the central design
change from the reference, where accelerators are generic custom resources
with GPU special-cases in the policy layer.
"""

from __future__ import annotations

from typing import Dict, ItemsView, Iterable, Mapping, Optional

PRECISION = 10000

CPU = "CPU"
TPU = "TPU"
GPU = "GPU"  # accepted for API compatibility; no special-casing anywhere
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

PREDEFINED = (CPU, TPU, GPU, MEMORY, OBJECT_STORE_MEMORY)

# Resources that represent individually addressable units (chip ids); the
# raylet hands out instance indices for these at lease time so the worker can
# carve its visible-device env (reference: _raylet.pyx:2176-2182).
UNIT_INSTANCE_RESOURCES = (TPU, GPU)


def _fp(v: float) -> int:
    return round(v * PRECISION)


class ResourceSet:
    """A demand or capacity: {resource_name: fixed-point quantity}."""

    __slots__ = ("_res",)

    def __init__(self, mapping: Optional[Mapping[str, float]] = None, _raw: Optional[Dict[str, int]] = None):
        if _raw is not None:
            self._res = {k: v for k, v in _raw.items() if v != 0}
        else:
            self._res = {k: _fp(v) for k, v in (mapping or {}).items() if _fp(v) != 0}

    @classmethod
    def from_raw(cls, raw: Dict[str, int]) -> "ResourceSet":
        return cls(_raw=raw)

    def get(self, name: str) -> float:
        return self._res.get(name, 0) / PRECISION

    def get_raw(self, name: str) -> int:
        return self._res.get(name, 0)

    def names(self):
        return self._res.keys()

    def items(self) -> ItemsView[str, int]:
        return self._res.items()

    def to_dict(self) -> Dict[str, float]:
        return {k: v / PRECISION for k, v in self._res.items()}

    def is_empty(self) -> bool:
        return not self._res

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._res.get(k, 0) >= v for k, v in self._res.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._res)
        for k, v in other._res.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet.from_raw(out)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._res)
        for k, v in other._res.items():
            out[k] = out.get(k, 0) - v
        return ResourceSet.from_raw(out)

    def clamped_nonnegative(self) -> "ResourceSet":
        return ResourceSet.from_raw({k: max(v, 0) for k, v in self._res.items()})

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._res == other._res

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (ResourceSet.from_raw, (dict(self._res),))


class NodeResources:
    """Total + available capacity of one node, plus labels.

    reference: NodeResourceSet (resource_set.h:143) + node labels
    (label_selector.h).  Unit-instance resources additionally track which
    instance ids (chip indices) are free, so TPU chips are allocated in
    ICI-topology-aligned blocks (tpu.py:16 TPU_VALID_CHIP_OPTIONS).
    """

    def __init__(self, total: ResourceSet, labels: Optional[Dict[str, str]] = None):
        self.total = total
        self.available = ResourceSet.from_raw(dict(total.items()))
        self.labels = dict(labels or {})
        # instance id -> free? for unit resources
        self.free_instances: Dict[str, list] = {}
        for name in UNIT_INSTANCE_RESOURCES:
            n = int(total.get(name))
            if n:
                self.free_instances[name] = list(range(n))

    def feasible(self, demand: ResourceSet) -> bool:
        return demand.is_subset_of(self.total)

    def can_allocate(self, demand: ResourceSet) -> bool:
        return demand.is_subset_of(self.available)

    def allocate(self, demand: ResourceSet) -> Optional[Dict[str, list]]:
        """Deduct; returns {unit_resource: [instance ids]} or None."""
        if not self.can_allocate(demand):
            return None
        instances: Dict[str, list] = {}
        for name in UNIT_INSTANCE_RESOURCES:
            want = int(demand.get(name))
            if want:
                free = self.free_instances.get(name, [])
                if len(free) < want:
                    return None
                instances[name] = free[:want]
        for name, ids in instances.items():
            self.free_instances[name] = self.free_instances[name][len(ids):]
        self.available = self.available - demand
        return instances

    def release(self, demand: ResourceSet, instances: Optional[Dict[str, list]] = None):
        self.available = self.available + demand
        # Clamp against total (defensive; double-release is a bug upstream).
        for k, v in list(self.available.items()):
            if v > self.total.get_raw(k):
                self.available._res[k] = self.total.get_raw(k)
        for name, ids in (instances or {}).items():
            free = self.free_instances.setdefault(name, [])
            for i in ids:
                if i not in free:
                    free.append(i)
            free.sort()

    def utilization(self) -> float:
        """max over resources of fraction-used; the hybrid policy's score
        (reference: hybrid_scheduling_policy.h:29-49)."""
        best = 0.0
        for k, total in self.total.items():
            if total <= 0:
                continue
            used = total - self.available.get_raw(k)
            best = max(best, used / total)
        return best

    def matches_labels(self, selector: Optional[Dict[str, str]]) -> bool:
        if not selector:
            return True
        return all(self.labels.get(k) == v for k, v in selector.items())

    def snapshot(self) -> dict:
        return {
            "total": self.total.to_dict(),
            "available": self.available.to_dict(),
            "labels": dict(self.labels),
        }
