"""@ray_tpu.remote functions.

reference: python/ray/remote_function.py:41 (RemoteFunction, _remote :314).
Options mirror the reference's: num_returns, num_cpus, num_tpus, resources,
max_retries, retry_exceptions, scheduling_strategy, runtime_env.
``num_tpus`` is first-class (the reference's ``num_gpus`` analog) and
validated against ICI-aligned chip blocks by the TPU accelerator manager.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private.accelerators import get_accelerator_manager
from ray_tpu._private.scheduler import SchedulingStrategy


def _normalize_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        resources["CPU"] = float(opts["num_cpus"])
    elif "CPU" not in resources:
        resources["CPU"] = 1.0
    if opts.get("num_tpus") is not None:
        mgr = get_accelerator_manager("TPU")
        ok, err = mgr.validate_resource_request_quantity(opts["num_tpus"])
        if not ok:
            raise ValueError(err)
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus") is not None:
        resources["GPU"] = float(opts["num_gpus"])
    if opts.get("memory") is not None:
        resources["memory"] = float(opts["memory"])
    if opts.get("accelerator_type"):
        resources[f"accelerator_type:{opts['accelerator_type']}"] = 0.001
    return resources


def _normalize_strategy(opts: Dict[str, Any]) -> SchedulingStrategy:
    strategy = opts.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        return SchedulingStrategy()
    if strategy == "SPREAD":
        return SchedulingStrategy(kind="spread")
    if isinstance(strategy, SchedulingStrategy):
        return strategy
    # Strategy objects from ray_tpu.util.scheduling_strategies
    return strategy.to_internal()


class RemoteFunction:
    def __init__(self, fn, **options):
        self._fn = fn
        self._options = options
        functools.update_wrapper(self, fn)

    def options(self, **new_options) -> "RemoteFunction":
        merged = {**self._options, **new_options}
        return RemoteFunction(self._fn, **merged)

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        opts = self._options
        return w.submit_task(
            self._fn,
            args,
            kwargs,
            name=opts.get("name") or self._fn.__name__,
            num_returns=opts.get("num_returns", 1),
            resources=_normalize_resources(opts),
            strategy=_normalize_strategy(opts),
            max_retries=opts.get("max_retries"),
            retry_exceptions=opts.get("retry_exceptions", False),
            runtime_env=opts.get("runtime_env"),
        )

    def bind(self, *args, **kwargs):
        """Bind into a lazy DAG (reference: python/ray/dag FunctionNode)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._fn.__name__!r} cannot be called directly; "
            f"use {self._fn.__name__}.remote()."
        )
