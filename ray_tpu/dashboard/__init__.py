"""Dashboard: HTTP JSON API over cluster state.

reference: python/ray/dashboard/ — DashboardHead (head.py:49) + per-node
agents serving cluster status, actors/tasks/objects listings, job info,
Prometheus metrics, and the Chrome-trace timeline.
"""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
