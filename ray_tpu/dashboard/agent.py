"""Per-node agent: stats, stack traces, CPU profiling.

reference: python/ray/dashboard/agent.py + dashboard/modules/reporter/ —
each node runs an agent the head queries for node/worker stats, py-spy
stack dumps, and profiling.  Here the agent rides the raylet's existing RPC
server (handlers Agent*, wired in raylet.py); stacks and profiles come from
the workers themselves (sys._current_frames / a sampling profiler in
worker.py), which needs no ptrace privileges the way py-spy does.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

_CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def _read_proc_stat() -> Optional[List[int]]:
    try:
        with open("/proc/stat") as f:
            fields = f.readline().split()[1:]
        return [int(x) for x in fields]
    except (OSError, ValueError):
        return None


def _meminfo() -> Dict[str, int]:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                name, _, rest = line.partition(":")
                out[name] = int(rest.split()[0]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return out


class NodeStatsCollector:
    """CPU% needs two /proc/stat samples; the collector keeps the last one."""

    def __init__(self):
        self._last = _read_proc_stat()
        self._last_t = time.monotonic()

    def cpu_percent(self) -> Optional[float]:
        cur = _read_proc_stat()
        if cur is None or self._last is None:
            return None
        total = sum(cur) - sum(self._last)
        idle = (cur[3] + cur[4]) - (self._last[3] + self._last[4])
        self._last, self._last_t = cur, time.monotonic()
        if total <= 0:
            return 0.0
        return round(100.0 * (total - idle) / total, 1)

    def collect(self, worker_pids: List[int]) -> Dict:
        mem = _meminfo()
        try:
            load = os.getloadavg()
        except OSError:
            load = (0.0, 0.0, 0.0)
        return {
            "cpu_percent": self.cpu_percent(),
            "cpus": os.cpu_count(),
            "load_avg": load,
            "mem_total": mem.get("MemTotal"),
            "mem_available": mem.get("MemAvailable"),
            "workers": [w for w in (worker_stats(p) for p in worker_pids) if w],
            "ts": time.time(),
        }


def worker_stats(pid: int) -> Optional[Dict]:
    """RSS + cumulative CPU seconds for one worker from /proc/<pid>."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().rsplit(") ", 1)[1].split()
        utime, stime = int(parts[11]), int(parts[12])
        rss_pages = int(parts[21])
        return {
            "pid": pid,
            "cpu_seconds": (utime + stime) / _CLK,
            "rss": rss_pages * os.sysconf("SC_PAGE_SIZE"),
        }
    except (OSError, ValueError, IndexError):
        return None
