"""Prometheus + Grafana wiring (reference: dashboard/modules/metrics/).

The reference writes prometheus scrape configs and Grafana provisioning +
dashboard JSONs into the session directory (modules/metrics/install_and_start
templates); operators point their Prometheus/Grafana at those files. Same
contract here: ``generate_configs(out_dir, metrics_url)`` materializes

    out_dir/prometheus.yml
    out_dir/grafana/provisioning/datasources/ray_tpu.yml
    out_dir/grafana/provisioning/dashboards/ray_tpu.yml
    out_dir/grafana/dashboards/{cluster,serve,slo,events,runtime,watch}.json

against the core metric names exported by the dashboard head's /metrics
(see head.py core_metrics_text): ray_tpu_nodes, ray_tpu_actors,
ray_tpu_resource_total/available, ray_tpu_tasks, ray_tpu_serve_replicas,
ray_tpu_serve_requests_total, ray_tpu_events_total, plus the built-in
runtime families from _private/runtime_metrics.py (runtime.json panels)
and any user metrics from ray_tpu.util.metrics.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List


def _panel(panel_id: int, title: str, exprs: List[str], x: int, y: int,
           kind: str = "timeseries", unit: str = "short") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": kind,
        "datasource": {"type": "prometheus", "uid": "ray_tpu_prom"},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [{"expr": e, "refId": chr(ord("A") + i),
                     "legendFormat": "__auto"} for i, e in enumerate(exprs)],
    }


def _dashboard(uid: str, title: str, panels: List[dict]) -> dict:
    return {
        "uid": uid,
        "title": title,
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
    }


def cluster_dashboard() -> dict:
    return _dashboard("ray-tpu-cluster", "ray_tpu cluster", [
        _panel(1, "Nodes", ["ray_tpu_nodes"], 0, 0),
        _panel(2, "Actors by state", ["ray_tpu_actors"], 12, 0),
        _panel(3, "Logical resources",
               ["ray_tpu_resource_total", "ray_tpu_resource_available"], 0, 8),
        _panel(4, "Tasks by state", ["ray_tpu_tasks"], 12, 8),
        _panel(5, "TPU chips",
               ['ray_tpu_resource_total{resource="TPU"}',
                'ray_tpu_resource_available{resource="TPU"}'], 0, 16),
        _panel(6, "Placement groups", ["ray_tpu_placement_groups"], 12, 16),
    ])


def serve_dashboard() -> dict:
    return _dashboard("ray-tpu-serve", "ray_tpu serve", [
        _panel(1, "Replicas", ["ray_tpu_serve_replicas"], 0, 0),
        _panel(2, "Request rate",
               ["rate(ray_tpu_serve_requests_total[5m])"], 12, 0, unit="reqps"),
        _panel(3, "Queue depth", ["ray_tpu_serve_queued"], 0, 8),
        _panel(4, "Apps", ["ray_tpu_serve_apps"], 12, 8),
    ])


def slo_dashboard() -> dict:
    """Serving SLO page (serve/_private/slo.py): sketch-derived tail
    latencies per deployment and tenant, error-budget burn rates per
    window/objective, route-decision forensics, terminal statuses."""
    return _dashboard("ray-tpu-slo", "ray_tpu serving SLOs", [
        _panel(1, "TTFT p50/p99 by deployment",
               ['ray_tpu_serve_ttft_seconds{quantile="0.5"}',
                'ray_tpu_serve_ttft_seconds{quantile="0.99"}'],
               0, 0, unit="s"),
        _panel(2, "Inter-token latency p50/p99 by deployment",
               ['ray_tpu_serve_itl_seconds{quantile="0.5"}',
                'ray_tpu_serve_itl_seconds{quantile="0.99"}'],
               12, 0, unit="s"),
        _panel(3, "SLO burn rate (5m/1h by objective; >1 = burning budget)",
               ["ray_tpu_serve_slo_burn_rate"], 0, 8),
        _panel(4, "Requests by terminal status (ok/error/aborted/shed)",
               ["rate(ray_tpu_serve_slo_requests_total[5m])"], 12, 8,
               unit="reqps"),
        _panel(5, "Per-tenant TTFT p99",
               ['ray_tpu_serve_ttft_seconds{quantile="0.99"}'], 0, 16,
               unit="s"),
        _panel(6, "Router decisions by reason",
               ["rate(ray_tpu_serve_route_decisions_total[5m])"], 12, 16,
               unit="reqps"),
        _panel(7, "Serving stage p99 (queue_wait/prefill/handoff/decode)",
               ['ray_tpu_serve_stage_seconds{quantile="0.99"}'], 0, 24,
               unit="s"),
        _panel(8, "Prefix-cache hit rate vs disagg queue depth",
               ["rate(ray_tpu_serve_prefix_cache_hits_total[5m])",
                "ray_tpu_serve_disagg_queue_depth"], 12, 24),
    ])


def events_dashboard() -> dict:
    return _dashboard("ray-tpu-events", "ray_tpu events", [
        _panel(1, "Events by severity",
               ["increase(ray_tpu_events_total[5m])"], 0, 0),
    ])


def runtime_dashboard() -> dict:
    """Built-in runtime metric families (_private/runtime_metrics.py):
    scheduler, worker pool, object store, task, collective, GCS, data."""
    return _dashboard("ray-tpu-runtime", "ray_tpu runtime", [
        _panel(1, "Scheduling latency p50/p99",
               ['histogram_quantile(0.5, rate(ray_tpu_scheduler_schedule_latency_seconds_bucket[5m]))',
                'histogram_quantile(0.99, rate(ray_tpu_scheduler_schedule_latency_seconds_bucket[5m]))'],
               0, 0, unit="s"),
        _panel(2, "Pending tasks by resource shape",
               ["ray_tpu_scheduler_pending_tasks"], 12, 0),
        _panel(3, "Worker pool by state", ["ray_tpu_raylet_workers"], 0, 8),
        _panel(4, "Worker spawn p50 by method",
               ['histogram_quantile(0.5, rate(ray_tpu_raylet_worker_spawn_seconds_bucket[5m]))',
                'rate(ray_tpu_raylet_zygote_fallback_total[5m])',
                'rate(ray_tpu_raylet_worker_spawn_timeout_total[5m])'],
               12, 8, unit="s"),
        _panel(5, "Object store bytes",
               ["ray_tpu_object_store_used_bytes",
                "rate(ray_tpu_object_store_spilled_bytes_total[5m])",
                "rate(ray_tpu_object_store_restored_bytes_total[5m])"],
               0, 16, unit="bytes"),
        _panel(6, "Task execution p50/p99",
               ['histogram_quantile(0.5, rate(ray_tpu_task_execution_seconds_bucket[5m]))',
                'histogram_quantile(0.99, rate(ray_tpu_task_execution_seconds_bucket[5m]))'],
               12, 16, unit="s"),
        _panel(7, "Collective bus bandwidth",
               ["ray_tpu_collective_bus_bandwidth_gbps"], 0, 24, unit="GBs"),
        _panel(8, "Collective bytes rate",
               ["rate(ray_tpu_collective_bytes_total[5m])"], 12, 24,
               unit="Bps"),
        _panel(9, "GCS RPC latency p99 by method",
               ['histogram_quantile(0.99, rate(ray_tpu_gcs_rpc_latency_seconds_bucket[5m]))'],
               0, 32, unit="s"),
        _panel(10, "Serve request latency p50/p99",
               ['histogram_quantile(0.5, rate(ray_tpu_serve_request_latency_seconds_bucket[5m]))',
                'histogram_quantile(0.99, rate(ray_tpu_serve_request_latency_seconds_bucket[5m]))'],
               12, 32, unit="s"),
        _panel(11, "Data rows/s",
               ["rate(ray_tpu_data_rows_total[5m])"], 0, 40, unit="rowsps"),
        _panel(12, "TPU chips (total vs claimed)",
               ["ray_tpu_tpu_chips"], 12, 40),
    ])


def _sparkline(panel_id: int, title: str, expr: str, x: int, y: int,
               unit: str = "short") -> dict:
    """Compact stat-with-sparkline: the history-panel shape for the watch
    dashboard's at-a-glance signal row."""
    p = _panel(panel_id, title, [expr], x, y, kind="stat", unit=unit)
    p["gridPos"] = {"h": 4, "w": 6, "x": x, "y": y}
    p["options"] = {"graphMode": "area", "colorMode": "value",
                    "reduceOptions": {"calcs": ["lastNotNull"]}}
    return p


def watch_dashboard() -> dict:
    """Watch rules + metrics history (_private/metrics_history.py): alert
    transition rates per rule, the history store's footprint against its
    byte cap, and sparkline history panels for every built-in rule-pack
    signal.  The same series are queryable without Prometheus at
    /api/metric_history (the in-GCS history store); these panels are the
    external-Grafana rendering of them."""
    return _dashboard("ray-tpu-watch", "ray_tpu watch & history", [
        _panel(1, "Watch alerts firing/cleared by rule",
               ['increase(ray_tpu_watch_alerts_total{state="firing"}[10m])',
                'increase(ray_tpu_watch_alerts_total{state="cleared"}[10m])'],
               0, 0),
        _panel(2, "History store footprint (bytes under the hard cap)",
               ["ray_tpu_metrics_history_bytes",
                "ray_tpu_metrics_history_series"], 12, 0, unit="bytes"),
        # sparkline row: the built-in rule pack's signals
        _sparkline(3, "KV block occupancy",
                   "ray_tpu_engine_kv_block_occupancy_ratio", 0, 8,
                   unit="percentunit"),
        _sparkline(4, "Decode queue depth",
                   "ray_tpu_serve_disagg_queue_depth", 6, 8),
        _sparkline(5, "Input-wait fraction",
                   "rate(ray_tpu_data_ingest_wait_seconds_total[5m])",
                   12, 8, unit="percentunit"),
        _sparkline(6, "JIT compiles/s",
                   "rate(ray_tpu_jit_compiles_total[5m])", 18, 8),
        _sparkline(7, "Straggler lag",
                   "ray_tpu_collective_straggler_lag_seconds", 0, 12,
                   unit="s"),
        _sparkline(8, "Goodput ratio", "ray_tpu_train_goodput_ratio",
                   6, 12, unit="percentunit"),
        _sparkline(9, "Serve availability burn (5m)",
                   'ray_tpu_serve_slo_burn_rate{window="5m",'
                   'objective="availability"}', 12, 12),
        _sparkline(10, "Live metric reporters",
                   'ray_tpu_gcs_sink_size{sink="metric_reporters"}',
                   18, 12),
    ])


def generate_configs(out_dir: str, metrics_url: str) -> Dict[str, str]:
    """Write all configs; returns {name: path}."""
    host_port = metrics_url.split("//", 1)[-1].rstrip("/")
    written: Dict[str, str] = {}
    os.makedirs(out_dir, exist_ok=True)

    prom = (
        "global:\n"
        "  scrape_interval: 10s\n"
        "scrape_configs:\n"
        "  - job_name: ray_tpu\n"
        "    metrics_path: /metrics\n"
        "    static_configs:\n"
        f"      - targets: ['{host_port}']\n"
    )
    p = os.path.join(out_dir, "prometheus.yml")
    with open(p, "w") as f:
        f.write(prom)
    written["prometheus"] = p

    ds_dir = os.path.join(out_dir, "grafana", "provisioning", "datasources")
    os.makedirs(ds_dir, exist_ok=True)
    p = os.path.join(ds_dir, "ray_tpu.yml")
    with open(p, "w") as f:
        f.write(
            "apiVersion: 1\n"
            "datasources:\n"
            "  - name: ray_tpu_prom\n"
            "    uid: ray_tpu_prom\n"
            "    type: prometheus\n"
            "    url: http://localhost:9090\n"
            "    isDefault: true\n")
    written["datasource"] = p

    prov_dir = os.path.join(out_dir, "grafana", "provisioning", "dashboards")
    os.makedirs(prov_dir, exist_ok=True)
    dash_dir = os.path.join(out_dir, "grafana", "dashboards")
    os.makedirs(dash_dir, exist_ok=True)
    p = os.path.join(prov_dir, "ray_tpu.yml")
    with open(p, "w") as f:
        f.write(
            "apiVersion: 1\n"
            "providers:\n"
            "  - name: ray_tpu\n"
            "    type: file\n"
            "    options:\n"
            f"      path: {dash_dir}\n")
    written["provider"] = p

    for name, dash in (("cluster", cluster_dashboard()),
                       ("serve", serve_dashboard()),
                       ("slo", slo_dashboard()),
                       ("events", events_dashboard()),
                       ("runtime", runtime_dashboard()),
                       ("watch", watch_dashboard())):
        p = os.path.join(dash_dir, f"{name}.json")
        with open(p, "w") as f:
            json.dump(dash, f, indent=2)
        written[f"dashboard_{name}"] = p
    return written
