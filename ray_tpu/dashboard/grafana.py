"""Prometheus + Grafana wiring (reference: dashboard/modules/metrics/).

The reference writes prometheus scrape configs and Grafana provisioning +
dashboard JSONs into the session directory (modules/metrics/install_and_start
templates); operators point their Prometheus/Grafana at those files. Same
contract here: ``generate_configs(out_dir, metrics_url)`` materializes

    out_dir/prometheus.yml
    out_dir/grafana/provisioning/datasources/ray_tpu.yml
    out_dir/grafana/provisioning/dashboards/ray_tpu.yml
    out_dir/grafana/dashboards/{cluster,serve,events}.json

against the core metric names exported by the dashboard head's /metrics
(see head.py core_metrics_text): ray_tpu_nodes, ray_tpu_actors,
ray_tpu_resource_total/available, ray_tpu_tasks, ray_tpu_serve_replicas,
ray_tpu_serve_requests_total, ray_tpu_events_total, plus any user metrics
from ray_tpu.util.metrics.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List


def _panel(panel_id: int, title: str, exprs: List[str], x: int, y: int,
           kind: str = "timeseries", unit: str = "short") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": kind,
        "datasource": {"type": "prometheus", "uid": "ray_tpu_prom"},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [{"expr": e, "refId": chr(ord("A") + i),
                     "legendFormat": "__auto"} for i, e in enumerate(exprs)],
    }


def _dashboard(uid: str, title: str, panels: List[dict]) -> dict:
    return {
        "uid": uid,
        "title": title,
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
    }


def cluster_dashboard() -> dict:
    return _dashboard("ray-tpu-cluster", "ray_tpu cluster", [
        _panel(1, "Nodes", ["ray_tpu_nodes"], 0, 0),
        _panel(2, "Actors by state", ["ray_tpu_actors"], 12, 0),
        _panel(3, "Logical resources",
               ["ray_tpu_resource_total", "ray_tpu_resource_available"], 0, 8),
        _panel(4, "Tasks by state", ["ray_tpu_tasks"], 12, 8),
        _panel(5, "TPU chips",
               ['ray_tpu_resource_total{resource="TPU"}',
                'ray_tpu_resource_available{resource="TPU"}'], 0, 16),
        _panel(6, "Placement groups", ["ray_tpu_placement_groups"], 12, 16),
    ])


def serve_dashboard() -> dict:
    return _dashboard("ray-tpu-serve", "ray_tpu serve", [
        _panel(1, "Replicas", ["ray_tpu_serve_replicas"], 0, 0),
        _panel(2, "Request rate",
               ["rate(ray_tpu_serve_requests_total[5m])"], 12, 0, unit="reqps"),
        _panel(3, "Queue depth", ["ray_tpu_serve_queued"], 0, 8),
        _panel(4, "Apps", ["ray_tpu_serve_apps"], 12, 8),
    ])


def events_dashboard() -> dict:
    return _dashboard("ray-tpu-events", "ray_tpu events", [
        _panel(1, "Events by severity",
               ["increase(ray_tpu_events_total[5m])"], 0, 0),
    ])


def generate_configs(out_dir: str, metrics_url: str) -> Dict[str, str]:
    """Write all configs; returns {name: path}."""
    host_port = metrics_url.split("//", 1)[-1].rstrip("/")
    written: Dict[str, str] = {}
    os.makedirs(out_dir, exist_ok=True)

    prom = (
        "global:\n"
        "  scrape_interval: 10s\n"
        "scrape_configs:\n"
        "  - job_name: ray_tpu\n"
        "    metrics_path: /metrics\n"
        "    static_configs:\n"
        f"      - targets: ['{host_port}']\n"
    )
    p = os.path.join(out_dir, "prometheus.yml")
    with open(p, "w") as f:
        f.write(prom)
    written["prometheus"] = p

    ds_dir = os.path.join(out_dir, "grafana", "provisioning", "datasources")
    os.makedirs(ds_dir, exist_ok=True)
    p = os.path.join(ds_dir, "ray_tpu.yml")
    with open(p, "w") as f:
        f.write(
            "apiVersion: 1\n"
            "datasources:\n"
            "  - name: ray_tpu_prom\n"
            "    uid: ray_tpu_prom\n"
            "    type: prometheus\n"
            "    url: http://localhost:9090\n"
            "    isDefault: true\n")
    written["datasource"] = p

    prov_dir = os.path.join(out_dir, "grafana", "provisioning", "dashboards")
    os.makedirs(prov_dir, exist_ok=True)
    dash_dir = os.path.join(out_dir, "grafana", "dashboards")
    os.makedirs(dash_dir, exist_ok=True)
    p = os.path.join(prov_dir, "ray_tpu.yml")
    with open(p, "w") as f:
        f.write(
            "apiVersion: 1\n"
            "providers:\n"
            "  - name: ray_tpu\n"
            "    type: file\n"
            "    options:\n"
            f"      path: {dash_dir}\n")
    written["provider"] = p

    for name, dash in (("cluster", cluster_dashboard()),
                       ("serve", serve_dashboard()),
                       ("events", events_dashboard())):
        p = os.path.join(dash_dir, f"{name}.json")
        with open(p, "w") as f:
            json.dump(dash, f, indent=2)
        written[f"dashboard_{name}"] = p
    return written
