"""Dashboard head: a threaded HTTP server exposing cluster state as JSON
plus a zero-build web UI at ``/``.

reference: dashboard/head.py:49 (DashboardHead) + modules — node/actor/task
listings (state API), jobs, /metrics Prometheus exposition
(_private/metrics_agent.py), timeline (Chrome trace).  The reference ships
a React app (dashboard/client/); this rebuild serves a single static page
(index.html, vanilla JS polling the same JSON endpoints) — no node/webpack
toolchain in the TPU image.

Endpoints:
  GET /api/version
  GET /api/cluster_status   nodes + aggregate resources
  GET /api/nodes            state API list_nodes
  GET /api/actors           list_actors
  GET /api/tasks            list_tasks (folded states)
  GET /api/objects          list_objects
  GET /api/placement_groups list_placement_groups
  GET /api/jobs             submitted jobs (job manager) + driver jobs (GCS)
  GET /api/timeline         Chrome trace events
  GET /api/trace/<trace_id> one distributed trace: spans + critical path
  GET /api/flight_recorder  per-process flight-recorder tails [?pid=&seconds=]
  GET /api/diagnose         cluster hang sweep (blocking members, stragglers)
  GET /api/goodput          train wall-clock by bucket per run [?run=]
  GET /api/slo              serving SLO report: percentiles, burn rates, breaches
  GET /api/recent_requests  newest completed serve requests [?limit=&tenant=]
  GET /api/utilization      device telemetry: per-replica slot/KV headroom [?deployment=]
  GET /api/ingress          admission gate + proxy tier + pool-autoscaler actuations
  GET /metrics              Prometheus exposition of cluster metrics
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

VERSION = "0.1.0"


def _jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _jsonable(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(_jsonable(k)): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "hex") and not isinstance(obj, (str, bytes, float, int)):
        return obj.hex()
    if isinstance(obj, bytes):
        return obj.decode("utf-8", errors="replace")
    return obj


class DashboardHead:
    """Serves the connected cluster's state over HTTP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        head = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                try:
                    body, ctype = head._route(self.path)
                    code = 200 if body is not None else 404
                except Exception as e:  # noqa: BLE001
                    body, ctype, code = json.dumps(
                        {"error": str(e)}).encode(), "application/json", 500
                if body is None:
                    body = b'{"error": "not found"}'
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="dashboard-head")
        self._thread.start()
        # opt-in usage telemetry (reference: usage_stats_head.py); no-op
        # unless RAY_TPU_USAGE_STATS_ENABLED=1
        from ray_tpu.dashboard.usage_stats import UsageStatsReporter

        self._usage_reporter = UsageStatsReporter()
        self._usage_reporter.start()

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def shutdown(self):
        self._usage_reporter.stop()
        self._server.shutdown()
        self._server.server_close()

    # -- routing --------------------------------------------------------

    def _route(self, path: str):
        from urllib.parse import parse_qs, urlsplit

        parts = urlsplit(path)
        query = parse_qs(parts.query)
        path = parts.path.rstrip("/") or "/"
        if path in ("/", "/index.html"):
            import os

            ui = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "index.html")
            with open(ui, "rb") as f:
                return f.read(), "text/html; charset=utf-8"
        if path == "/metrics":
            from ray_tpu.util.metrics import prometheus_text

            body = prometheus_text() + self._core_metrics_text()
            return body.encode(), "text/plain; version=0.0.4"
        data = self._api(path, query)
        if data is None:
            return None, None
        return json.dumps(_jsonable(data)).encode(), "application/json"

    def _api(self, path: str, query=None):
        from ray_tpu.util import state

        if path == "/api/version":
            return {"version": VERSION}
        if path == "/api/cluster_status":
            import ray_tpu

            return {
                "nodes": state.list_nodes(),
                "cluster_resources": ray_tpu.cluster_resources(),
                "available_resources": ray_tpu.available_resources(),
            }
        if path == "/api/nodes":
            return state.list_nodes()
        if path == "/api/actors":
            return state.list_actors()
        if path == "/api/tasks":
            return state.list_tasks()
        if path == "/api/objects":
            return state.list_objects()
        if path == "/api/placement_groups":
            return state.list_placement_groups()
        if path == "/api/jobs":
            out = {"driver_jobs": state.list_jobs(), "submissions": []}
            try:
                import ray_tpu
                from ray_tpu.job.job_manager import _JOB_MANAGER_NAME

                # existing manager only — a GET must not create one
                mgr = ray_tpu.get_actor(_JOB_MANAGER_NAME)
                out["submissions"] = ray_tpu.get(mgr.list_jobs.remote())
            except Exception:  # noqa: BLE001 — no submissions yet
                pass
            return out
        if path == "/api/timeline":
            import ray_tpu

            return ray_tpu.timeline()
        if path.startswith("/api/trace/"):
            # /api/trace/<trace_id> — every span of one distributed trace
            # plus the critical-path summary (util/tracing.py context)
            trace_id = path[len("/api/trace/"):]
            if not trace_id:
                return None
            client = state.StateApiClient()
            spans = client.get_trace(trace_id)
            return {
                "trace_id": trace_id,
                "spans": spans,
                # reuse the fetched spans — no second event-log fold
                "summary": client.summarize_trace(trace_id, spans=spans),
            }
        if path == "/api/node_stats":
            return state.node_stats()
        if path == "/api/node_metrics":
            # per-node Prometheus exposition (each raylet's metrics agent);
            # /metrics stays the cluster-wide aggregate.  ?node_id=<hex>
            # narrows to one node.
            nid = (query or {}).get("node_id", [None])[0]
            return state.node_metrics(nid)
        if path == "/api/stacks":
            return state.dump_stacks()
        if path == "/api/native_stacks":
            # /api/native_stacks?pid=N — C/XLA frames of a wedged worker
            pid = int((query or {}).get("pid", ["0"])[0])
            return state.dump_native_stacks(pid)
        if path == "/api/flight_recorder":
            # ?pid=N&seconds=S — per-process flight-recorder tails (live
            # workers over RPC, dead ones from their crash-dump files)
            q = query or {}
            pid = q.get("pid", [None])[0]
            seconds = q.get("seconds", [None])[0]
            return state.flight_recorder(
                pid=int(pid) if pid else None,
                seconds=float(seconds) if seconds else None)
        if path == "/api/diagnose":
            # one cluster-wide hang sweep: blocking collective members,
            # straggler scores, recorder tails, cross-linked trace ids
            q = query or {}
            timeout = q.get("hang_timeout_s", [None])[0]
            return state.diagnose(
                hang_timeout_s=float(timeout) if timeout else None,
                source="dashboard")
        if path == "/api/goodput":
            # published goodput ledgers: wall-clock by bucket per train run
            run = (query or {}).get("run", [None])[0]
            return state.goodput(run)
        if path == "/api/slo":
            # cluster serving SLO report: sketch percentiles (per
            # deployment/tenant/stage), burn rates per window/objective,
            # breach list.  ?deployment=<name> narrows.
            dep = (query or {}).get("deployment", [None])[0]
            return state.serving_slo(dep)
        if path == "/api/utilization":
            # device telemetry: per-deployment replica rows (free decode
            # slots, free KV blocks, duty cycle, HBM split) + summed
            # headroom — the autoscaler's input surface.  ?deployment=
            # narrows.
            dep = (query or {}).get("deployment", [None])[0]
            return state.utilization(dep)
        if path == "/api/recent_requests":
            # overload forensics: newest completed requests cluster-wide
            # [?limit=&deployment=&tenant=]
            q = query or {}
            return state.recent_requests(
                limit=int(q.get("limit", ["100"])[0]),
                deployment=q.get("deployment", [None])[0],
                tenant=q.get("tenant", [None])[0])
        if path == "/api/metric_history":
            # in-GCS time-series of the cluster metric aggregate
            # [?family=&tags=<json>&window_s=&step_s=&op=&q=]; without
            # family: retained families + store stats
            import json as _json

            q = query or {}
            tags_raw = q.get("tags", [None])[0]
            window = q.get("window_s", [None])[0]
            step = q.get("step_s", [None])[0]
            return state.metric_history(
                family=q.get("family", [None])[0],
                tags=_json.loads(tags_raw) if tags_raw else None,
                window_s=float(window) if window else None,
                step_s=float(step) if step else None,
                op=q.get("op", [None])[0],
                q=float(q.get("q", ["0.99"])[0]))
        if path == "/api/alerts":
            # watch-engine state: active alerts, rules, recent
            # transitions [?rule=<name> narrows]
            return state.alerts((query or {}).get("rule", [None])[0])
        if path == "/api/ingress":
            # ingress control plane: admission gate (weights, per-tenant
            # inflight), scale-out tier backends, pool-autoscaler
            # pools + recent actuations
            return state.ingress()
        if path == "/api/events":
            return state.list_cluster_events()
        if path == "/api/serve":
            return self._serve_view()
        if path == "/api/train":
            return self._train_view()
        if path == "/api/data":
            return self._data_view()
        if path == "/api/grafana":
            return self._grafana_view()
        return None

    # -- per-library views (reference: dashboard/modules/{serve,train,data})

    def _serve_view(self):
        import time as _time

        import ray_tpu
        from ray_tpu.serve._private.controller import CONTROLLER_NAME

        # TTL cache: the UI poll and every /metrics scrape share one
        # snapshot, so replica-stats probes run at most once per window
        cached = getattr(self, "_serve_cache", None)
        if cached is not None and _time.monotonic() - cached[0] < 5.0:
            return cached[1]

        try:
            ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
        except Exception:  # noqa: BLE001 — serve not running
            view = {"running": False, "applications": {}}
            self._serve_cache = (_time.monotonic(), view)
            return view
        apps = {}
        for app in ray_tpu.get(ctrl.list_applications.remote()):
            desc = ray_tpu.get(ctrl.describe_application.remote(app))
            stats = {}
            for name in desc:
                reps = ray_tpu.get(ctrl.get_deployment_stats.remote(app, name))
                stats[name] = [r for r in reps if r]
            apps[app] = {"deployments": desc, "stats": stats}
        view = {"running": True, "applications": apps}
        self._serve_cache = (_time.monotonic(), view)
        return view

    def _train_view(self):
        """Every live TrainControllerActor's status (v2 runs)."""
        import time as _time

        import ray_tpu
        from ray_tpu.util import state

        controllers = [a for a in state.list_actors()
                       if a.get("class_name") == "TrainControllerActor"
                       and a.get("state") == "ALIVE"]
        # submit all probes first, collect under ONE shared deadline
        # (serial 5s-per-controller would stall the dashboard thread)
        probes = []
        for a in controllers:
            ref = None
            try:
                if a.get("name"):
                    ref = ray_tpu.get_actor(a["name"]).get_status.remote()
            except Exception:  # noqa: BLE001 — controller gone: its probe row stays empty
                pass
            probes.append((a, ref))
        deadline = _time.monotonic() + 5
        runs = []
        for a, ref in probes:
            status = {}
            if ref is not None:
                try:
                    status = ray_tpu.get(
                        ref, timeout=max(0.1, deadline - _time.monotonic()))
                except Exception:  # noqa: BLE001 — probe timeout: render partial status
                    pass
            runs.append({"actor_id": a["actor_id"], "name": a.get("name"),
                         "status": status})
        return {"runs": runs}

    def _data_view(self):
        """Published streaming-executor runs (data:stats:* in the GCS KV)."""
        import json as _json

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        if w is None:
            return {"runs": []}
        keys = w.gcs.call("KVKeys", {"prefix": "data:stats:"}) or []
        blobs = w.gcs.call("KVMultiGet", {"keys": sorted(keys)[-50:]}) or {}
        return {"runs": [_json.loads(v) for v in blobs.values()]}

    def _grafana_view(self):
        """Generate (once) and report the Prometheus/Grafana config files."""
        import tempfile

        from ray_tpu.dashboard import grafana

        if not hasattr(self, "_grafana_paths"):
            out = getattr(self, "metrics_config_dir", None) or \
                tempfile.mkdtemp(prefix="ray_tpu_metrics_")
            self._grafana_paths = grafana.generate_configs(out, self.url)
        return self._grafana_paths

    # -- core metric exposition (reference: dashboard/modules/metrics +
    #    src/ray/stats/metric_defs.cc) — computed at scrape time

    def _core_metrics_text(self) -> str:
        from collections import Counter as _Counter

        import ray_tpu
        from ray_tpu.util import state

        lines = []

        def gauge(name, value, **tags):
            t = ",".join(f'{k}="{v}"' for k, v in tags.items())
            lines.append(f"{name}{{{t}}} {value}" if t else f"{name} {value}")

        try:
            nodes = state.list_nodes()
            by_state = _Counter(n.get("state", "ALIVE") for n in nodes)
            for s, c in by_state.items():
                gauge("ray_tpu_nodes", c, state=s)
            for res, v in ray_tpu.cluster_resources().items():
                gauge("ray_tpu_resource_total", v, resource=res)
            for res, v in ray_tpu.available_resources().items():
                gauge("ray_tpu_resource_available", v, resource=res)
            actors = _Counter(a.get("state") for a in state.list_actors())
            for s, c in actors.items():
                gauge("ray_tpu_actors", c, state=s)
            pgs = _Counter(p.get("state")
                           for p in state.list_placement_groups())
            for s, c in pgs.items():
                gauge("ray_tpu_placement_groups", c, state=s)
            tasks = _Counter(t.get("state") for t in state.list_tasks())
            for s, c in tasks.items():
                gauge("ray_tpu_tasks", c, state=s)
            # monotonic totals from the GCS (the event ring evicts, so a
            # count over list_cluster_events would DECREASE and break
            # Prometheus rate()/increase() semantics)
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker()
            counts = (w.gcs.call("GetEventCounts", {}) or {}) if w else {}
            for s, c in counts.items():
                gauge("ray_tpu_events_total", c, severity=s)
        except Exception:  # noqa: BLE001 — scrape must not 500 mid-shutdown
            pass
        try:
            serve = self._serve_view()
            if serve["running"]:
                gauge("ray_tpu_serve_apps", len(serve["applications"]))
                for app, dep in serve["applications"].items():
                    for name, reps in dep.get("stats", {}).items():
                        gauge("ray_tpu_serve_replicas", len(reps),
                              app=app, deployment=name)
                        gauge("ray_tpu_serve_requests_total",
                              sum(r.get("total", 0) for r in reps),
                              app=app, deployment=name)
                        gauge("ray_tpu_serve_queued",
                              sum(r.get("ongoing", 0) for r in reps),
                              app=app, deployment=name)
        except Exception:  # noqa: BLE001 — serve rows are optional; scrape must not 500
            pass
        return "\n" + "\n".join(lines) + "\n" if lines else ""


_dashboard: Optional[DashboardHead] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> DashboardHead:
    """Start (or return) the process-wide dashboard head."""
    global _dashboard
    if _dashboard is None:
        _dashboard = DashboardHead(host, port)
    return _dashboard
