"""Dashboard head: a threaded HTTP server exposing cluster state as JSON
plus a zero-build web UI at ``/``.

reference: dashboard/head.py:49 (DashboardHead) + modules — node/actor/task
listings (state API), jobs, /metrics Prometheus exposition
(_private/metrics_agent.py), timeline (Chrome trace).  The reference ships
a React app (dashboard/client/); this rebuild serves a single static page
(index.html, vanilla JS polling the same JSON endpoints) — no node/webpack
toolchain in the TPU image.

Endpoints:
  GET /api/version
  GET /api/cluster_status   nodes + aggregate resources
  GET /api/nodes            state API list_nodes
  GET /api/actors           list_actors
  GET /api/tasks            list_tasks (folded states)
  GET /api/objects          list_objects
  GET /api/placement_groups list_placement_groups
  GET /api/jobs             submitted jobs (job manager) + driver jobs (GCS)
  GET /api/timeline         Chrome trace events
  GET /metrics              Prometheus exposition of cluster metrics
"""

from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

VERSION = "0.1.0"


def _jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _jsonable(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(_jsonable(k)): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "hex") and not isinstance(obj, (str, bytes, float, int)):
        return obj.hex()
    if isinstance(obj, bytes):
        return obj.decode("utf-8", errors="replace")
    return obj


class DashboardHead:
    """Serves the connected cluster's state over HTTP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        head = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                try:
                    body, ctype = head._route(self.path)
                    code = 200 if body is not None else 404
                except Exception as e:  # noqa: BLE001
                    body, ctype, code = json.dumps(
                        {"error": str(e)}).encode(), "application/json", 500
                if body is None:
                    body = b'{"error": "not found"}'
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="dashboard-head")
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()

    # -- routing --------------------------------------------------------

    def _route(self, path: str):
        path = path.split("?", 1)[0].rstrip("/") or "/"
        if path in ("/", "/index.html"):
            import os

            ui = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "index.html")
            with open(ui, "rb") as f:
                return f.read(), "text/html; charset=utf-8"
        if path == "/metrics":
            from ray_tpu.util.metrics import prometheus_text

            return prometheus_text().encode(), "text/plain; version=0.0.4"
        data = self._api(path)
        if data is None:
            return None, None
        return json.dumps(_jsonable(data)).encode(), "application/json"

    def _api(self, path: str):
        from ray_tpu.util import state

        if path == "/api/version":
            return {"version": VERSION}
        if path == "/api/cluster_status":
            import ray_tpu

            return {
                "nodes": state.list_nodes(),
                "cluster_resources": ray_tpu.cluster_resources(),
                "available_resources": ray_tpu.available_resources(),
            }
        if path == "/api/nodes":
            return state.list_nodes()
        if path == "/api/actors":
            return state.list_actors()
        if path == "/api/tasks":
            return state.list_tasks()
        if path == "/api/objects":
            return state.list_objects()
        if path == "/api/placement_groups":
            return state.list_placement_groups()
        if path == "/api/jobs":
            out = {"driver_jobs": state.list_jobs(), "submissions": []}
            try:
                import ray_tpu
                from ray_tpu.job.job_manager import _JOB_MANAGER_NAME

                # existing manager only — a GET must not create one
                mgr = ray_tpu.get_actor(_JOB_MANAGER_NAME)
                out["submissions"] = ray_tpu.get(mgr.list_jobs.remote())
            except Exception:  # noqa: BLE001 — no submissions yet
                pass
            return out
        if path == "/api/timeline":
            import ray_tpu

            return ray_tpu.timeline()
        if path == "/api/node_stats":
            return state.node_stats()
        if path == "/api/stacks":
            return state.dump_stacks()
        return None


_dashboard: Optional[DashboardHead] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> DashboardHead:
    """Start (or return) the process-wide dashboard head."""
    global _dashboard
    if _dashboard is None:
        _dashboard = DashboardHead(host, port)
    return _dashboard
