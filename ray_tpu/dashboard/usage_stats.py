"""Usage telemetry + export-event sinks (opt-in, off by default).

reference: dashboard/modules/usage_stats/usage_stats_head.py (periodic
usage reports to a collector URL) and src/ray/protobuf/export_*.proto
(structured event export for external observability pipelines).  Both are
fleet-observability plumbing: a cluster periodically summarizes what it
is (version, nodes, resources, which libraries are in use) and ships
that plus its event stream to operator-configured sinks.

Here the same contract, privacy-first and zero-egress-safe:

  - DISABLED unless ``RAY_TPU_USAGE_STATS_ENABLED=1`` (the reference
    ships enabled-by-default telemetry; this deployment's images are
    zero-egress, so opt-in is the only sane default)
  - sinks: always a local JSON file (``usage_stats.json`` in the session
    temp dir or ``RAY_TPU_USAGE_STATS_FILE``); additionally an HTTP POST
    when ``RAY_TPU_USAGE_STATS_URL`` is set (injectable transport, like
    the BigQuery/ClickHouse connectors)
  - export events: ``export_cluster_events(path)`` appends the cluster
    event stream as JSONL — the export_*.proto capability without a
    proto toolchain (recorded decision: pickle/JSON wire formats)
"""

from __future__ import annotations

import json
import logging
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_LIBRARIES = ("data", "train", "tune", "serve", "llm", "rllib", "dag")


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "0") == "1"


def _library_usage() -> Dict[str, bool]:
    """Which ray_tpu libraries this process has imported (the reference
    tracks library usage the same way: by recording import touchpoints)."""
    return {lib: f"ray_tpu.{lib}" in sys.modules for lib in _LIBRARIES}


def collect_usage_report() -> Dict[str, Any]:
    """One usage snapshot (schema mirrors the reference's UsageStats)."""
    report: Dict[str, Any] = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "collected_at": time.time(),
        "python_version": sys.version.split()[0],
        "platform": sys.platform,
        "library_usage": _library_usage(),
    }
    try:
        from ray_tpu.util import state

        nodes = state.list_nodes()
        report["num_nodes"] = len(
            [n for n in nodes if n.get("state") != "DEAD"])
        total: Dict[str, float] = {}
        for n in nodes:
            # node rows carry {"resources": {"total": {...}, ...}}
            res = (n.get("resources") or {}).get("total") or {}
            for k, v in res.items():
                total[k] = total.get(k, 0.0) + float(v)
        report["total_resources"] = total
    except Exception:  # noqa: BLE001 — no cluster: process-local report
        report["num_nodes"] = 0
        report["total_resources"] = {}
    return report


def default_report_path() -> str:
    return os.environ.get(
        "RAY_TPU_USAGE_STATS_FILE",
        os.path.join(tempfile.gettempdir(), "ray_tpu_usage_stats.json"))


def write_usage_report(report: Optional[Dict[str, Any]] = None, *,
                       transport=None) -> Dict[str, Any]:
    """Write one report to the configured sinks; returns the report.
    ``transport``: injectable callable(url, payload_bytes) for tests."""
    report = report or collect_usage_report()
    path = default_report_path()
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    url = os.environ.get("RAY_TPU_USAGE_STATS_URL")
    if url:
        payload = json.dumps(report).encode()
        try:
            if transport is not None:
                transport(url, payload)
            else:
                import urllib.request

                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=10).read()
        except Exception:  # noqa: BLE001 — telemetry must never break work
            pass
    return report


def export_cluster_events(path: str, *, since_ts: float = 0.0) -> int:
    """Append the cluster event stream to ``path`` as JSONL (the
    export_*.proto event-sink capability); returns events written."""
    from ray_tpu.util import state

    events = state.list_cluster_events()
    n = 0
    with open(path, "a") as f:
        for ev in events:
            if float(ev.get("ts", 0)) < since_ts:  # events carry 'ts'
                continue
            f.write(json.dumps(ev, default=str) + "\n")
            n += 1
    return n


class UsageStatsReporter:
    """Background periodic reporter (started by the dashboard head when
    enabled; interval via RAY_TPU_USAGE_STATS_INTERVAL_S, default 300)."""

    def __init__(self, interval_s: Optional[float] = None):
        if interval_s is None:
            interval_s = float(
                os.environ.get("RAY_TPU_USAGE_STATS_INTERVAL_S", "300"))
        self.interval_s = max(1.0, interval_s)  # 0 would busy-loop
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if not usage_stats_enabled() or self._thread is not None:
            return
        # telemetry must never break (or block) work: every report —
        # including the immediate first one — runs guarded on the
        # background thread, never on the caller's (DashboardHead.__init__)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="usage-stats")
        self._thread.start()

    def _loop(self):
        while True:
            try:
                write_usage_report()
            except Exception as e:  # noqa: BLE001 — telemetry must never
                # break work, but a report that fails EVERY interval
                # should at least be debuggable
                logger.debug("usage report failed: %s", e)
            if self._stop.wait(self.interval_s):
                return

    def stop(self):
        self._stop.set()
