"""Trial state.

reference: python/ray/tune/experiment/trial.py (Trial status lifecycle
PENDING/RUNNING/PAUSED/TERMINATED/ERROR).
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Any, Dict, List, Optional


PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = dataclasses.field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    metrics: Dict[str, Any] = dataclasses.field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    checkpoint_path: Optional[str] = None
    training_iteration: int = 0
    # PBT plumbing (set by the scheduler, consumed by the controller)
    pbt_exploit_from: Optional["Trial"] = None
    pbt_new_config: Optional[Dict[str, Any]] = None

    def __hash__(self):
        return hash(self.trial_id)

    def __eq__(self, other):
        return isinstance(other, Trial) and self.trial_id == other.trial_id

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.metrics
