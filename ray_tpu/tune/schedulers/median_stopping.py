"""Median stopping rule.

reference: python/ray/tune/schedulers/median_stopping_rule.py: stop a trial
at time t if its best result so far is worse than the median of other
trials' running averages at t.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._trial_history: Dict[Any, List[float]] = defaultdict(list)

    def _signed(self, v: float) -> float:
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return self.CONTINUE
        self._trial_history[trial].append(self._signed(value))
        if t < self.grace:
            return self.CONTINUE
        others = [sum(h) / len(h) for tr, h in self._trial_history.items()
                  if tr is not trial and h]
        if len(others) < self.min_samples:
            return self.CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._trial_history[trial])
        return self.STOP if best < median else self.CONTINUE
