"""Synchronous HyperBand (reference: python/ray/tune/schedulers/hyperband.py).

Trials are grouped into brackets; each bracket runs its trials to a rung
budget, then halves synchronously: the bottom 1-1/eta fraction is stopped
and survivors continue to the next rung (milestone *= eta).  Unlike ASHA
(async_hyperband.py), a rung only halves when every live trial in the
bracket reached the milestone, giving fair comparisons at the cost of
stragglers."""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _Bracket:
    def __init__(self, min_t: int, max_t: int, eta: float):
        self.eta = eta
        self.max_t = max_t
        self.milestone = min_t
        self.trials: List[Any] = []
        self.at_milestone: Dict[Any, float] = {}  # trial -> metric at rung
        self.dropped: set = set()

    def ready_to_halve(self) -> bool:
        live = [t for t in self.trials if t not in self.dropped]
        return live and all(t in self.at_milestone for t in live)

    def halve(self) -> set:
        """Returns the set of trials to stop; advances the milestone."""
        ranked = sorted(self.at_milestone, key=self.at_milestone.get)
        keep = max(1, int(len(ranked) / self.eta))
        losers = set(ranked[:-keep]) if len(ranked) > keep else set()
        self.dropped |= losers
        self.at_milestone = {}
        self.milestone = min(int(self.milestone * self.eta), self.max_t)
        return losers


class HyperBandScheduler(TrialScheduler):
    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # bracket sizes follow the HyperBand schedule s = s_max..0
        self._s_max = int(math.log(max_t, self.eta))
        self._brackets: List[_Bracket] = []
        self._bracket_of: Dict[Any, _Bracket] = {}
        self._next_bracket_s = self._s_max

    def _new_bracket(self) -> _Bracket:
        s = self._next_bracket_s
        self._next_bracket_s = s - 1 if s > 0 else self._s_max
        min_t = max(1, int(self.max_t / (self.eta ** s)))
        b = _Bracket(min_t, self.max_t, self.eta)
        self._brackets.append(b)
        return b

    def _bracket_capacity(self, s: int) -> int:
        return max(1, int(math.ceil((self._s_max + 1) * (self.eta ** s)
                                    / (s + 1))))

    def on_trial_add(self, trial):
        for b in self._brackets:
            s = round(math.log(self.max_t / b.milestone, self.eta)) if b.milestone else 0
            if not b.at_milestone and not b.dropped \
                    and len(b.trials) < self._bracket_capacity(max(s, 0)):
                b.trials.append(trial)
                self._bracket_of[trial] = b
                return
        b = self._new_bracket()
        b.trials.append(trial)
        self._bracket_of[trial] = b

    def _signed(self, v) -> float:
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        b = self._bracket_of.get(trial)
        if b is None:
            return self.CONTINUE
        if trial in b.dropped:
            return self.STOP  # lost an earlier halving; stop at next report
        t = result.get(self.time_attr, 0)
        if t >= b.max_t:
            return self.STOP
        value = result.get(self.metric)
        if t < b.milestone or value is None:
            return self.CONTINUE
        # reached the rung: park the FIRST at-rung score (stragglers may keep
        # training past the milestone; their later results must not shift the
        # comparison budget); once the whole rung is in, halve
        b.at_milestone.setdefault(trial, self._signed(value))
        if not b.ready_to_halve():
            return self.CONTINUE
        losers = b.halve()
        # losers that aren't `trial` are stopped via their own next result;
        # mark them so on_trial_result STOPs them immediately
        return self.STOP if trial in losers else self.CONTINUE

    def on_trial_complete(self, trial, result):
        b = self._bracket_of.pop(trial, None)
        if b is not None:
            b.dropped.add(trial)
            b.at_milestone.pop(trial, None)
            if b.at_milestone and b.ready_to_halve():
                b.halve()

    def choose_trial_to_run(self, pending):
        # prefer trials whose bracket is mid-rung (unblocks synchronous halving)
        for t in pending:
            b = self._bracket_of.get(t)
            if b is not None and t not in b.dropped:
                return t
        return pending[0] if pending else None

    def is_dropped(self, trial) -> bool:
        b = self._bracket_of.get(trial)
        return b is not None and trial in b.dropped