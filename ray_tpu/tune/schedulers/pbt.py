"""Population based training.

reference: python/ray/tune/schedulers/pbt.py: at each perturbation_interval,
bottom-quantile trials exploit (load the checkpoint + config of a
top-quantile trial) and explore (mutate hyperparams by resample or
perturbation factors 1.2/0.8).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.search.sample import Domain


class PopulationBasedTraining(TrialScheduler):
    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[Any, int] = {}
        self._latest: Dict[Any, float] = {}

    def _signed(self, v) -> float:
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is not None:
            self._latest[trial] = self._signed(value)
        if t - self._last_perturb.get(trial, 0) < self.interval:
            return self.CONTINUE
        self._last_perturb[trial] = t
        ranked = sorted(self._latest, key=self._latest.get)  # worst..best
        if len(ranked) < 2:
            return self.CONTINUE
        n_q = max(1, int(len(ranked) * self.quantile))
        bottom, top = ranked[:n_q], ranked[-n_q:]
        if trial in bottom:
            donor = self.rng.choice(top)
            # the controller performs the actual exploit/explore restart
            trial.pbt_exploit_from = donor
            trial.pbt_new_config = self._explore(dict(donor.config))
            return self.PAUSE
        return self.CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if self.rng.random() < self.resample_prob or not isinstance(
                    config[key], (int, float)):
                if isinstance(spec, Domain):
                    config[key] = spec.sample(self.rng)
                elif isinstance(spec, (list, tuple)):
                    config[key] = self.rng.choice(list(spec))
                elif callable(spec):
                    config[key] = spec()
            else:
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                config[key] = type(config[key])(config[key] * factor)
        return config
