"""PB2: Population Based Bandits (reference: python/ray/tune/schedulers/pb2.py).

PBT's exploit step stays (bottom-quantile trials restart from a top-quantile
donor's checkpoint); the explore step replaces PBT's random
perturb/resample with a GP-bandit suggestion: fit a Gaussian process to
(normalized hyperparams, time) -> reward-change observations and pick the
candidate maximizing UCB = mu + kappa * sigma (Parker-Holder et al., 2020).
The reference wraps GPy; here the GP (RBF kernel + Cholesky solve) is ~40
lines of numpy."""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining

import numpy as np


class _GP:
    """Minimal RBF-kernel GP regression (zero mean, unit signal)."""

    def __init__(self, X: np.ndarray, y: np.ndarray,
                 length_scale: float = 0.3, noise: float = 1e-2):
        self.X = X
        self.ls = length_scale
        y = y.astype(np.float64)
        self.y_mean = float(y.mean()) if len(y) else 0.0
        self.y_std = float(y.std()) or 1.0
        self.y = (y - self.y_mean) / self.y_std
        K = self._k(X, X) + noise * np.eye(len(X))
        self.L = np.linalg.cholesky(K)
        self.alpha = np.linalg.solve(self.L.T, np.linalg.solve(self.L, self.y))

    def _k(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        Ks = self._k(Xs, self.X)
        mu = Ks @ self.alpha
        v = np.linalg.solve(self.L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu * self.y_std + self.y_mean, np.sqrt(var) * self.y_std


class PB2(PopulationBasedTraining):
    def __init__(self, *args, hyperparam_bounds: Optional[Dict[str, Tuple[float, float]]] = None,
                 kappa: float = 2.0, n_candidates: int = 64, **kwargs):
        """``hyperparam_bounds``: {name: (low, high)} continuous ranges the
        GP searches over (PB2 is defined for continuous hyperparams; pass
        categorical ones through ``hyperparam_mutations`` as in PBT)."""
        super().__init__(*args, **kwargs)
        self.bounds = hyperparam_bounds or {}
        self.kappa = kappa
        self.n_candidates = n_candidates
        # (t, config values, reward delta) observations per the PB2 paper
        self._data: List[Tuple[float, Dict[str, float], float]] = []
        self._prev_score: Dict[Any, Tuple[float, float]] = {}  # trial -> (t, score)

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is not None:
            prev = self._prev_score.get(trial)
            score = self._signed(value)
            if prev is not None and t > prev[0]:
                delta = (score - prev[1]) / (t - prev[0])
                cfg = {k: float(trial.config[k]) for k in self.bounds
                       if k in trial.config}
                if cfg:
                    self._data.append((float(t), cfg, delta))
            self._prev_score[trial] = (t, score)
        decision = super().on_trial_result(trial, result)
        if decision == self.PAUSE:
            # exploit restarts from the donor's checkpoint: the score jump
            # to the donor's level is NOT evidence about the new config
            self._prev_score.pop(trial, None)
        return decision

    # -- GP-UCB explore ----------------------------------------------------

    def _normalize(self, t: float, cfg: Dict[str, float]) -> List[float]:
        tmax = max((d[0] for d in self._data), default=1.0) or 1.0
        row = [t / tmax]
        for k, (lo, hi) in sorted(self.bounds.items()):
            span = (hi - lo) or 1.0
            row.append((cfg.get(k, lo) - lo) / span)
        return row

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        config = dict(config)
        if not self.bounds:
            return super()._explore(config)
        if len(self._data) < 4:
            for k, (lo, hi) in self.bounds.items():
                config[k] = self.rng.uniform(lo, hi)
            return config
        X = np.array([self._normalize(t, cfg) for t, cfg, _ in self._data])
        y = np.array([d for _, _, d in self._data])
        try:
            gp = _GP(X, y)
        except np.linalg.LinAlgError:
            return super()._explore(config)
        t_now = max(d[0] for d in self._data)
        cands = []
        for _ in range(self.n_candidates):
            c = {k: self.rng.uniform(lo, hi) for k, (lo, hi) in self.bounds.items()}
            cands.append(c)
        Xs = np.array([self._normalize(t_now, c) for c in cands])
        mu, sigma = gp.predict(Xs)
        best = int(np.argmax(mu + self.kappa * sigma))
        for k, v in cands[best].items():
            # preserve int-typed hyperparams
            config[k] = type(config.get(k, v))(v) if isinstance(
                config.get(k), int) else v
        return config
