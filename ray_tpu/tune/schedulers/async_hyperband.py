"""ASHA: asynchronous successive halving.

reference: python/ray/tune/schedulers/async_hyperband.py
(AsyncHyperBandScheduler/ASHAScheduler): rungs at grace_period *
reduction_factor^k; a trial reaching a rung continues only if its metric is
in the top 1/reduction_factor of results recorded at that rung.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class AsyncHyperBandScheduler(TrialScheduler):
    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3,
        brackets: int = 1,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.milestones: List[float] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        # recorded metric values per rung
        self._rungs: Dict[float, List[float]] = defaultdict(list)
        self._trial_last_rung: Dict[Any, float] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        value = float(value) if self.mode == "max" else -float(value)
        decision = self.CONTINUE
        for rung in self.milestones:
            if t >= rung and self._trial_last_rung.get(trial, -1) < rung:
                self._trial_last_rung[trial] = rung
                recorded = self._rungs[rung]
                recorded.append(value)
                k = max(1, int(len(recorded) / self.rf))
                top_k = sorted(recorded, reverse=True)[:k]
                cutoff = top_k[-1]
                if value < cutoff:
                    decision = self.STOP
        return decision


ASHAScheduler = AsyncHyperBandScheduler
