"""Trial scheduler ABC + FIFO.

reference: python/ray/tune/schedulers/trial_scheduler.py (TrialScheduler
CONTINUE/PAUSE/STOP decisions, on_trial_result hook).
"""

from __future__ import annotations

from typing import Any, Dict


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    def on_trial_add(self, trial) -> None:  # noqa: B027
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, trial, result: Dict[str, Any]) -> None:  # noqa: B027
        pass

    def choose_trial_to_run(self, pending):  # first runnable by default
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference: trial_scheduler.py FIFO)."""
