from ray_tpu.tune.schedulers.trial_scheduler import (
    FIFOScheduler,
    TrialScheduler,
)
from ray_tpu.tune.schedulers.async_hyperband import ASHAScheduler, AsyncHyperBandScheduler
from ray_tpu.tune.schedulers.bohb import HyperBandForBOHB
from ray_tpu.tune.schedulers.median_stopping import MedianStoppingRule
from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler
from ray_tpu.tune.schedulers.pb2 import PB2
from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining

__all__ = [
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "HyperBandForBOHB",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "HyperBandScheduler",
    "PB2",
]
