"""HyperBand for BOHB (reference: python/ray/tune/schedulers/hb_bohb.py).

Same bracket/rung structure as the synchronous HyperBandScheduler; the BOHB
variant changes the FILL ORDER: trials are admitted to the OLDEST
still-filling bracket and the runner is steered to finish earlier brackets
first, so low-budget rungs complete early and the ``BOHBSearcher``'s
per-budget KDE models (search/bohb.py) get observations before the later,
larger-budget brackets are suggested — the information flow BOHB's model
fitting depends on.
"""

from __future__ import annotations

from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler


class HyperBandForBOHB(HyperBandScheduler):
    def choose_trial_to_run(self, pending):
        # earliest bracket first (the base class picks any mid-rung trial):
        # finishing bracket k's rungs before starting k+1 maximizes the
        # observations available to the searcher's budget models
        for b in self._brackets:
            for t in pending:
                if self._bracket_of.get(t) is b and t not in b.dropped:
                    return t
        return super().choose_trial_to_run(pending)
