"""ResultGrid: the outcome of Tuner.fit().

reference: python/ray/tune/result_grid.py (get_best_result, get_dataframe).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    error: Optional[str]
    checkpoint_path: Optional[str]
    path: str


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str] = None,
                 mode: str = "min"):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> TrialResult:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric or pass one)")
        candidates = [r for r in self._results if metric in r.metrics]
        if not candidates:
            raise RuntimeError("no trial reported the requested metric")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(candidates, key=key) if mode == "max" else min(candidates, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["trial_id"] = r.trial_id
            for k, v in r.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)
