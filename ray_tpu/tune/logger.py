"""Trial loggers + callback hooks.

reference: python/ray/tune/logger/ (CSV/JSON/TensorBoard trial loggers
written into each trial dir by default) and tune/callback.py (Callback
hooks driven by the controller's event loop).
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional


class Callback:
    """reference: tune/callback.py — controller-loop hooks."""

    def on_trial_result(self, iteration: int, trial, result: Dict[str, Any]) -> None:  # noqa: B027
        pass

    def on_trial_complete(self, iteration: int, trial) -> None:  # noqa: B027
        pass

    def on_trial_error(self, iteration: int, trial) -> None:  # noqa: B027
        pass


def _trial_dir(trial) -> Optional[str]:
    return getattr(trial, "local_dir", None)


class JsonLoggerCallback(Callback):
    """One JSON line per reported result -> <trial_dir>/result.json
    (reference: tune/logger/json.py)."""

    def on_trial_result(self, iteration, trial, result):
        d = _trial_dir(trial)
        if not d:
            return
        with open(os.path.join(d, "result.json"), "a") as f:
            f.write(json.dumps({**result, "trial_id": trial.trial_id},
                               default=str) + "\n")


class CSVLoggerCallback(Callback):
    """Tabular results -> <trial_dir>/progress.csv (reference:
    tune/logger/csv.py).  Columns are fixed by the first result."""

    def __init__(self):
        self._writers: Dict[str, tuple] = {}  # trial_id -> (file, writer, fields)

    def on_trial_result(self, iteration, trial, result):
        d = _trial_dir(trial)
        if not d:
            return
        entry = self._writers.get(trial.trial_id)
        if entry is None:
            fields = sorted(k for k, v in result.items()
                            if isinstance(v, (int, float, str, bool)))
            f = open(os.path.join(d, "progress.csv"), "a", newline="")
            writer = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            if f.tell() == 0:
                writer.writeheader()
            entry = (f, writer, fields)
            self._writers[trial.trial_id] = entry
        f, writer, _ = entry
        writer.writerow({k: v for k, v in result.items()})
        f.flush()

    def on_trial_complete(self, iteration, trial):
        entry = self._writers.pop(trial.trial_id, None)
        if entry:
            entry[0].close()

    on_trial_error = on_trial_complete


class TBXLoggerCallback(Callback):
    """TensorBoard scalars (reference: tune/logger/tensorboardx.py); gated
    on tensorboardX, which this image does not ship."""

    def __init__(self):
        try:
            import tensorboardX  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "tensorboardX is not installed; the default CSV/JSON "
                "loggers are always active") from e
        self._writers: Dict[str, Any] = {}

    def on_trial_result(self, iteration, trial, result):
        from tensorboardX import SummaryWriter

        w = self._writers.get(trial.trial_id)
        if w is None:
            w = self._writers[trial.trial_id] = SummaryWriter(_trial_dir(trial))
        step = result.get("training_iteration", iteration)
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)

    def on_trial_complete(self, iteration, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w:
            w.close()

    on_trial_error = on_trial_complete


DEFAULT_CALLBACKS = (JsonLoggerCallback, CSVLoggerCallback)


def default_callbacks() -> List[Callback]:
    return [cls() for cls in DEFAULT_CALLBACKS]
