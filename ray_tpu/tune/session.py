"""Tune worker-side session: tune.report / tune.get_checkpoint.

reference: ray.tune uses the shared ray.train session (train/_internal/session.py);
here likewise — the tune trial actor hosts a train session underneath.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal import session as train_session


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    train_session.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return train_session.get_checkpoint()
