"""Search algorithms (reference: python/ray/tune/search/)."""

from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.search.optuna import HyperOptSearch, OptunaSearch
from ray_tpu.tune.search.searcher import (
    ConcurrencyLimiter,
    RandomSearcher,
    Repeater,
    Searcher,
)
from ray_tpu.tune.search.bohb import BOHBSearcher
from ray_tpu.tune.search.gp import GPSearcher
from ray_tpu.tune.search.tpe import TPESearcher

__all__ = [
    "BasicVariantGenerator",
    "Searcher",
    "RandomSearcher",
    "ConcurrencyLimiter",
    "Repeater",
    "GPSearcher",
    "TPESearcher",
    "BOHBSearcher",
    "OptunaSearch",
    "HyperOptSearch",
]
