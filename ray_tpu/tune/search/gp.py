"""Native Gaussian-process Bayesian-optimization searcher (GP-EI).

reference surface: the reference ships model-based searchers as thin
wrappers over external libraries — ax (tune/search/ax/ax_search.py:43),
bayesopt, hebo, nevergrad — none of which are in this image.  The
capability class (a GP surrogate + acquisition optimization in suggest
mode) is implemented here natively on the same RBF GP the PB2 scheduler
already uses (tune/schedulers/pb2.py:_GP) and the framework's own Domain
primitives (VERDICT r4 missing #3).

Algorithm: after ``n_startup`` random trials, fit a zero-mean RBF GP to
the observations with every searchable dimension normalized to [0, 1]
(log-domains in log space; categoricals by smoothed index — adequate for
small cardinalities, the same simplification PB2 makes), then suggest
the candidate maximizing Expected Improvement over ``n_candidates``
random probes plus local perturbations of the incumbent.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Domain
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.tpe import _Dim, _flatten, _set_path


class GPSearcher(Searcher):
    """Suggest-mode Bayesian optimization with an RBF GP + EI acquisition."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "min",
                 n_startup: int = 8, n_candidates: int = 256,
                 xi: float = 0.01, seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.xi = xi
        self._rng = random.Random(seed)
        self._dims: List[Tuple[Tuple[str, ...], _Dim]] = []
        self._constants: List[Tuple[Tuple[str, ...], Any]] = []
        if space:
            self._build(space)
        self._suggested: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        self._obs: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []

    def _build(self, space: Dict[str, Any]):
        for path, spec in _flatten(space):
            if isinstance(spec, Domain):
                self._dims.append((path, _Dim(spec)))
            else:
                self._constants.append((path, spec))

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config and not self._dims and not self._constants:
            self._build(config)
        return True

    # -- unit-cube encoding -------------------------------------------

    def _bounds(self, dim: _Dim) -> Tuple[float, float]:
        if dim.kind == "cat":
            return 0.0, max(1.0, float(len(dim.categories) - 1))
        return float(dim.low), float(dim.high)

    def _to_unit(self, dim: _Dim, v) -> Optional[float]:
        x = dim.encode(v)
        if x is None:
            return None
        lo, hi = self._bounds(dim)
        return (x - lo) / (hi - lo) if hi > lo else 0.5

    def _from_unit(self, dim: _Dim, u: float):
        lo, hi = self._bounds(dim)
        return dim.decode(lo + min(max(u, 0.0), 1.0) * (hi - lo))

    # -- searcher API --------------------------------------------------

    @staticmethod
    def _modelable(dim: _Dim) -> bool:
        # sample_from and single-category choices carry no geometry the
        # GP can use; they are drawn from the domain directly, like TPE
        return dim.kind != "raw" and not (
            dim.kind == "cat" and len(dim.categories) < 2)

    def suggest(self, trial_id: str):
        values: Dict[Tuple[str, ...], Any] = {}
        model_dims = [(p, d) for p, d in self._dims if self._modelable(d)]
        unit = iter(self._propose_unit(model_dims) if model_dims else ())
        for path, dim in self._dims:
            if self._modelable(dim):
                values[path] = self._from_unit(dim, next(unit))
            else:
                values[path] = dim.random(self._rng)
        self._suggested[trial_id] = values
        cfg: Dict[str, Any] = {}
        for path, v in values.items():
            _set_path(cfg, path, v)
        for path, v in self._constants:
            _set_path(cfg, path, v)
        return cfg

    def _propose_unit(self, model_dims) -> List[float]:
        d = len(model_dims)
        rand = [self._rng.random() for _ in range(d)]
        X, y = [], []
        for values, val in self._obs:
            row = []
            for path, dim in model_dims:
                u = self._to_unit(dim, values.get(path))
                if u is None:
                    break
                row.append(u)
            else:
                X.append(row)
                y.append(val if self.mode == "max" else -val)
        if len(X) < self.n_startup:
            return rand
        # deferred: schedulers.pb2 imports tune.search at module load
        from ray_tpu.tune.schedulers.pb2 import _GP

        Xa = np.asarray(X, np.float64)
        ya = np.asarray(y, np.float64)
        gp = _GP(Xa, ya, length_scale=0.2)
        best = float(ya.max())
        inc = Xa[int(np.argmax(ya))]
        # candidate pool: global random probes + local perturbations of the
        # incumbent (classic BO candidate strategy without an inner optimizer)
        n_loc = self.n_candidates // 4
        local = [[min(max(inc[j] + self._rng.gauss(0, 0.1), 0), 1)
                  for j in range(d)] for _ in range(n_loc)]
        probes = [[self._rng.random() for _ in range(d)]
                  for _ in range(self.n_candidates - n_loc)]
        cand = np.asarray(probes + local, np.float64).reshape(-1, d)
        mu, sigma = gp.predict(cand)
        z = (mu - best - self.xi) / np.maximum(sigma, 1e-9)
        # EI = (mu - best - xi) Phi(z) + sigma phi(z)
        phi = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        Phi = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = (mu - best - self.xi) * Phi + sigma * phi
        return cand[int(np.argmax(ei))].tolist()

    def on_trial_complete(self, trial_id: str, result=None, error: bool = False):
        values = self._suggested.pop(trial_id, None)
        if values is None or error or not result:
            return
        val = result.get(self.metric) if self.metric else None
        if val is None:
            return
        self._obs.append((values, float(val)))
