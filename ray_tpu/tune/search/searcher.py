"""Suggest-based search algorithms.

reference: python/ray/tune/search/searcher.py (Searcher ABC),
search/concurrency_limiter.py (ConcurrencyLimiter), search/repeater.py
(Repeater) — the controller asks the searcher for configs one trial at a
time and reports results back, unlike the up-front BasicVariantGenerator.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.search.sample import Domain


class Searcher:
    """suggest(trial_id) returns a config dict, None ("wait, I need more
    results before suggesting"), or Searcher.FINISHED (search exhausted)."""

    FINISHED = "FINISHED"

    metric: Optional[str] = None
    mode: str = "min"

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str):
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:  # noqa: B027
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:  # noqa: B027
        pass


class RandomSearcher(Searcher):
    """Independent random draws from the space — the suggest-mode analog of
    BasicVariantGenerator's sampling half."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 seed: Optional[int] = None):
        self._space = space or {}
        self._rng = random.Random(seed)

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config and not self._space:
            self._space = config
        return True

    def suggest(self, trial_id: str):
        return _sample_space(self._space, self._rng)


def _sample_space(space: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    out = {}
    for k, v in space.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict) and "grid_search" in v and len(v) == 1:
            out[k] = rng.choice(list(v["grid_search"]))
        elif isinstance(v, dict):
            out[k] = _sample_space(v, rng)
        else:
            out[k] = v
    return out


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: search/concurrency_limiter.py).

    suggest() returns None while ``max_concurrent`` suggested trials have not
    yet completed, which makes the controller idle-wait instead of launching.
    """

    def __init__(self, searcher: Searcher, max_concurrent: int, batch: bool = False):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self.batch = batch
        self._live: set = set()

    def set_search_properties(self, metric, mode, config):
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None
        suggestion = self.searcher.suggest(trial_id)
        if suggestion is not None and suggestion != Searcher.FINISHED:
            self._live.add(trial_id)
        return suggestion

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class Repeater(Searcher):
    """Runs each suggested config ``repeat`` times and reports the MEAN
    metric to the wrapped searcher — variance control for noisy objectives
    (reference: search/repeater.py)."""

    def __init__(self, searcher: Searcher, repeat: int = 3):
        assert repeat >= 1
        self.searcher = searcher
        self.repeat = repeat
        self._group_of: Dict[str, str] = {}       # trial_id -> group leader id
        self._group_cfg: Dict[str, Dict] = {}     # leader id -> config
        self._group_left: Dict[str, int] = {}     # leader id -> remaining to hand out
        self._group_results: Dict[str, list] = {}  # leader id -> completed metrics

    def set_search_properties(self, metric, mode, config):
        ok = self.searcher.set_search_properties(metric, mode, config)
        self.metric = self.searcher.metric
        self.mode = self.searcher.mode
        return ok

    def suggest(self, trial_id: str):
        # hand out pending repeats of an open group first
        for leader, left in self._group_left.items():
            if left > 0:
                self._group_left[leader] = left - 1
                self._group_of[trial_id] = leader
                return dict(self._group_cfg[leader])
        suggestion = self.searcher.suggest(trial_id)
        if suggestion is None or suggestion == Searcher.FINISHED:
            return suggestion
        self._group_of[trial_id] = trial_id
        self._group_cfg[trial_id] = suggestion
        self._group_left[trial_id] = self.repeat - 1
        self._group_results[trial_id] = []
        return dict(suggestion)

    def on_trial_complete(self, trial_id, result=None, error=False):
        leader = self._group_of.pop(trial_id, None)
        if leader is None:
            return
        group = self._group_results.get(leader)
        if group is None:
            return
        metric = self.metric or self.searcher.metric
        if not error and result and metric in result:
            group.append(result[metric])
        # the group is done when all repeats were handed out AND none is
        # still running
        done = (self._group_left.get(leader, 0) == 0
                and self._pending_in_group(leader) == 0)
        if done:
            mean = sum(group) / len(group) if group else None
            agg = dict(result or {})
            if mean is not None and metric:
                agg[metric] = mean
            self.searcher.on_trial_complete(leader, agg, error=not group)
            self._group_cfg.pop(leader, None)
            self._group_left.pop(leader, None)
            self._group_results.pop(leader, None)

    def _pending_in_group(self, leader: str) -> int:
        return sum(1 for g in self._group_of.values() if g == leader)
