"""Native Tree-structured Parzen Estimator searcher.

reference surface: python/ray/tune/search/optuna/optuna_search.py — the
reference wraps optuna (whose default sampler is TPE); this environment has
no optuna, so the TPE itself is implemented here on the framework's own
Domain primitives (sample.py), and OptunaSearch/HyperOptSearch stay thin
gated wrappers for API parity.

Algorithm (Bergstra et al., NeurIPS 2011): after ``n_startup`` random
trials, split observations at the ``gamma`` quantile into good/bad sets, fit
a Parzen (Gaussian-kernel) density to each, and suggest the candidate
maximizing l_good(x)/l_bad(x) among ``n_candidates`` draws from the good
density.  Categorical/int dimensions use smoothed count ratios.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search.sample import (
    Choice,
    Domain,
    LogUniform,
    QUniform,
    Randint,
    Uniform,
)
from ray_tpu.tune.search.searcher import Searcher


def _flatten(space: Dict[str, Any], prefix: Tuple[str, ...] = ()):
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, Domain):
            yield path, v
        elif isinstance(v, dict) and "grid_search" in v and len(v) == 1:
            yield path, Choice(list(v["grid_search"]))
        elif isinstance(v, dict):
            yield from _flatten(v, path)
        else:
            yield path, v  # constant


def _set_path(d: Dict, path: Tuple[str, ...], value):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


class _Dim:
    """One searchable dimension, normalized to a numeric or categorical view."""

    def __init__(self, domain: Domain):
        self.domain = domain
        if isinstance(domain, Choice):
            self.kind = "cat"
            self.categories = domain.categories
        elif isinstance(domain, Randint):
            self.kind = "int"
            self.low, self.high = domain.low, domain.high - 1
        elif isinstance(domain, LogUniform):
            self.kind = "log"
            self.low, self.high = domain.log_low, domain.log_high
        elif isinstance(domain, QUniform):
            self.kind = "float"
            self.low, self.high = domain.low, domain.high
            self.q = domain.q
        elif isinstance(domain, Uniform):
            self.kind = "float"
            self.low, self.high = domain.low, domain.high
        else:  # SampleFrom / unknown: fall back to raw sampling, no model
            self.kind = "raw"

    # numeric encoding of an observed value
    def encode(self, v) -> Optional[float]:
        if self.kind == "cat":
            try:
                return float(self.categories.index(v))
            except ValueError:
                return None
        if self.kind == "log":
            return math.log(v) if v > 0 else None
        if self.kind in ("int", "float"):
            return float(v)
        return None

    def decode(self, x: float):
        if self.kind == "cat":
            return self.categories[int(round(x))]
        if self.kind == "log":
            return math.exp(min(max(x, self.low), self.high))
        if self.kind == "int":
            return int(round(min(max(x, self.low), self.high)))
        v = min(max(x, self.low), self.high)
        if hasattr(self, "q"):
            v = round(v / self.q) * self.q
        return v

    def random(self, rng: random.Random):
        return self.domain.sample(rng)


class TPESearcher(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "min",
                 n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._dims: List[Tuple[Tuple[str, ...], _Dim]] = []
        self._constants: List[Tuple[Tuple[str, ...], Any]] = []
        if space:
            self._build(space)
        self._suggested: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        self._obs: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []

    def _build(self, space: Dict[str, Any]):
        for path, spec in _flatten(space):
            if isinstance(spec, Domain):
                self._dims.append((path, _Dim(spec)))
            else:
                self._constants.append((path, spec))

    def set_search_properties(self, metric, mode, config):
        super().set_search_properties(metric, mode, config)
        if config and not self._dims and not self._constants:
            self._build(config)
        return True

    # ------------------------------------------------------------------

    def suggest(self, trial_id: str):
        flat: Dict[Tuple[str, ...], Any] = {}
        use_model = len(self._obs) >= self.n_startup
        for path, dim in self._dims:
            if use_model and dim.kind != "raw":
                flat[path] = self._suggest_dim(path, dim)
            else:
                flat[path] = dim.random(self._rng)
        self._suggested[trial_id] = flat
        config: Dict[str, Any] = {}
        for path, v in self._constants:
            _set_path(config, path, v)
        for path, v in flat.items():
            _set_path(config, path, v)
        return config

    def _split(self) -> Tuple[list, list]:
        ranked = sorted(self._obs, key=lambda o: o[1], reverse=True)  # best first
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_dim(self, path, dim: _Dim):
        good, bad = self._split()
        gx = [dim.encode(o[0][path]) for o in good if path in o[0]]
        bx = [dim.encode(o[0][path]) for o in bad if path in o[0]]
        gx = [x for x in gx if x is not None]
        bx = [x for x in bx if x is not None]
        if not gx:
            return dim.random(self._rng)
        if dim.kind == "cat":
            n = len(dim.categories)
            gcounts = [1.0] * n
            for x in gx:
                gcounts[int(x)] += 1
            bcounts = [1.0] * n
            for x in bx:
                bcounts[int(x)] += 1
            gsum, bsum = sum(gcounts), sum(bcounts)
            scores = [(gcounts[i] / gsum) / (bcounts[i] / bsum) for i in range(n)]
            # sample proportional to the good density, pick best ratio among draws
            best_i = max(
                self._rng.choices(range(n), weights=gcounts, k=self.n_candidates),
                key=lambda i: scores[i])
            return dim.categories[best_i]
        # continuous / int / log: Parzen windows around good points
        lo = min(gx + bx)
        hi = max(gx + bx)
        spread = (hi - lo) or abs(hi) or 1.0
        bw = max(spread / max(len(gx), 1) ** 0.5, 1e-6 * spread)

        def density(x: float, pts: List[float]) -> float:
            if not pts:
                return 1e-12
            s = 0.0
            for p in pts:
                z = (x - p) / bw
                s += math.exp(-0.5 * z * z)
            return s / (len(pts) * bw)

        best_x, best_score = None, -1.0
        for _ in range(self.n_candidates):
            center = self._rng.choice(gx)
            x = self._rng.gauss(center, bw)
            score = density(x, gx) / max(density(x, bx), 1e-12)
            if score > best_score:
                best_x, best_score = x, score
        return dim.decode(best_x)

    # ------------------------------------------------------------------

    def on_trial_complete(self, trial_id, result=None, error=False):
        flat = self._suggested.pop(trial_id, None)
        if flat is None or error or not result:
            return
        metric = self.metric
        if metric is None or metric not in result:
            return
        value = float(result[metric])
        signed = value if self.mode == "max" else -value
        self._obs.append((flat, signed))
