"""Search-space primitives.

reference: python/ray/tune/search/sample.py (uniform, loguniform, choice,
randint, quniform, grid_search).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.log_low, self.log_high = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_low, self.log_high))


class QUniform(Domain):
    def __init__(self, low: float, high: float, q: float):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return round(v / self.q) * self.q


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class SampleFrom(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def quniform(low: float, high: float, q: float) -> QUniform:
    return QUniform(low, high, q)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(categories: Sequence[Any]) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}
