"""Variant generation: grid expansion × random sampling.

reference: python/ray/tune/search/basic_variant.py (BasicVariantGenerator).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Tuple

from ray_tpu.tune.search.sample import Domain, GridSearch


def _find_special(space: Dict[str, Any], prefix: Tuple[str, ...] = ()):
    """Walk the (possibly nested) param space; yield (path, spec) for grids
    and domains."""
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict) and "grid_search" in v and len(v) == 1:
            yield path, GridSearch(v["grid_search"])
        elif isinstance(v, GridSearch):
            yield path, v
        elif isinstance(v, Domain):
            yield path, v
        elif isinstance(v, dict):
            yield from _find_special(v, path)


def _set_path(d: Dict, path: Tuple[str, ...], value: Any):
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _deep_copy_resolved(space: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in space.items():
        if isinstance(v, dict) and not ("grid_search" in v and len(v) == 1):
            out[k] = _deep_copy_resolved(v)
        else:
            out[k] = v
    return out


class BasicVariantGenerator:
    """Expands grid_search cartesian-product × num_samples random draws."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: int | None = None):
        self.space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)

    def variants(self) -> Iterator[Dict[str, Any]]:
        specials = list(_find_special(self.space))
        grid_paths = [(p, s) for p, s in specials if isinstance(s, GridSearch)]
        domain_paths = [(p, s) for p, s in specials if isinstance(s, Domain)]
        grid_axes = [[(p, v) for v in s.values] for p, s in grid_paths] or [[]]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_axes) if grid_paths else [()]:
                cfg = _deep_copy_resolved(self.space)
                for p, v in combo:
                    _set_path(cfg, p, v)
                for p, dom in domain_paths:
                    _set_path(cfg, p, dom.sample(self.rng))
                yield cfg

    def count(self) -> int:
        specials = list(_find_special(self.space))
        n = 1
        for _, s in specials:
            if isinstance(s, GridSearch):
                n *= len(s.values)
        return n * self.num_samples
