"""Optuna-COMPATIBLE searchers (reference: tune/search/optuna/optuna_search.py).

These are NOT bindings to the optuna/hyperopt packages: suggestions come
from the native [[TPESearcher]] (the same TPE algorithm both packages
default to). The import gate exists purely so code written against the
reference fails with the same error when the package is missing; when the
package IS present, a warning states that the native sampler is used.
Count Tune's search parity on TPESearcher, not on these names."""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ray_tpu.tune.search.searcher import Searcher

logger = logging.getLogger(__name__)


class OptunaSearch(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "min", **kwargs):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "optuna is not installed. Use ray_tpu.tune.search.tpe."
                "TPESearcher — the native implementation of optuna's default "
                "TPE sampler — or install optuna.") from e
        logger.warning(
            "OptunaSearch delegates to the native TPESearcher (optuna's "
            "default sampler); optuna's own samplers/pruners are not used.")
        from ray_tpu.tune.search.tpe import TPESearcher

        self._impl = TPESearcher(space, metric=metric, mode=mode, **kwargs)

    def set_search_properties(self, metric, mode, config):
        return self._impl.set_search_properties(metric, mode, config)

    def suggest(self, trial_id):
        return self._impl.suggest(trial_id)

    def on_trial_result(self, trial_id, result):
        self._impl.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._impl.on_trial_complete(trial_id, result, error)


class HyperOptSearch(OptunaSearch):
    """reference: tune/search/hyperopt/hyperopt_search.py — hyperopt is also
    TPE-based; same gating and native fallback."""

    def __init__(self, space=None, metric=None, mode="min", **kwargs):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "hyperopt is not installed. Use ray_tpu.tune.search.tpe."
                "TPESearcher (hyperopt's algorithm is TPE) or install "
                "hyperopt.") from e
        logger.warning(
            "HyperOptSearch delegates to the native TPESearcher (the same "
            "TPE algorithm); hyperopt itself is not used.")
        from ray_tpu.tune.search.tpe import TPESearcher

        self._impl = TPESearcher(space, metric=metric, mode=mode, **kwargs)
