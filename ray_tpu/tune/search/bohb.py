"""BOHB searcher: Bayesian optimization over HyperBand budgets.

reference surface: python/ray/tune/search/bohb/bohb_search.py — the
reference wraps hpbandster's KDE machinery; this environment has no
hpbandster, so BOHB's model (Falkner et al., ICML 2018) is built natively
on the in-repo TPE: observations are bucketed by the BUDGET they were
measured at (the scheduler's rung milestones in ``time_attr`` units), and
suggestions come from the model of the LARGEST budget with enough
observations — low-budget rungs bootstrap the model, high-budget rungs
refine it, exactly BOHB's information flow.

Pairs with ``HyperBandForBOHB`` (schedulers/bohb.py); works standalone too
(every report lands in the budget bucket of its training_iteration).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.tune.search.tpe import TPESearcher


class BOHBSearcher(TPESearcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "min",
                 time_attr: str = "training_iteration",
                 n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(space, metric=metric, mode=mode, n_startup=n_startup,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        self.time_attr = time_attr
        # budget -> [(flat config, signed metric)], latest report per trial
        self._by_budget: Dict[int, Dict[str, tuple]] = {}

    # -- observation routing -------------------------------------------

    def _record(self, trial_id: str, result: Dict[str, Any]):
        flat = self._suggested.get(trial_id)
        if flat is None or not result or self.metric not in result:
            return
        budget = int(result.get(self.time_attr, 1))
        value = float(result[self.metric])
        signed = value if self.mode == "max" else -value
        self._by_budget.setdefault(budget, {})[trial_id] = (flat, signed)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        if not error and result:
            self._record(trial_id, result)
        self._suggested.pop(trial_id, None)

    # -- model selection ------------------------------------------------

    def suggest(self, trial_id: str):
        # fit on the largest budget with >= n_startup observations
        # (BOHB's "use the highest-fidelity model that is trustworthy")
        self._obs = []
        for budget in sorted(self._by_budget, reverse=True):
            obs = list(self._by_budget[budget].values())
            if len(obs) >= self.n_startup:
                self._obs = obs
                break
        return super().suggest(trial_id)
