"""ray_tpu.tune — hyperparameter search over trial actors.

reference: python/ray/tune/ (SURVEY §2.3): Tuner + controller event loop,
variant generation, ASHA / median-stopping / PBT schedulers, trial-per-slice
placement via TuneConfig.trial_resources (e.g. {"TPU": 4}).
"""

from ray_tpu.tune.experiment import Trial
from ray_tpu.tune.result_grid import ResultGrid, TrialResult
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    ConcurrencyLimiter,
    RandomSearcher,
    Repeater,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.search.sample import (
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.logger import (
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    TBXLoggerCallback,
)
from ray_tpu.tune.session import get_checkpoint, report
from ray_tpu.tune.tuner import TuneConfig, TuneController, Tuner

__all__ = [
    "Tuner",
    "TuneConfig",
    "TuneController",
    "Trial",
    "ResultGrid",
    "TrialResult",
    "report",
    "get_checkpoint",
    "uniform",
    "loguniform",
    "quniform",
    "randint",
    "choice",
    "grid_search",
    "sample_from",
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "AsyncHyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "HyperBandScheduler",
    "PB2",
    "Searcher",
    "RandomSearcher",
    "ConcurrencyLimiter",
    "Repeater",
    "TPESearcher",
    "Callback",
    "CSVLoggerCallback",
    "JsonLoggerCallback",
    "TBXLoggerCallback",
]
