"""Tuner + TuneController.

reference: python/ray/tune/tuner.py:43 (Tuner, fit :312) and
tune/execution/tune_controller.py:68 — the event loop: start trials up to
resource limits, poll running trials, feed results to the scheduler, act on
CONTINUE/PAUSE/STOP, until all trials terminate. PBT exploit/explore is a
checkpoint-restore restart with a mutated config (schedulers/pbt.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train._internal.worker_group import RayTrainWorker
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.experiment import (
    ERROR,
    PAUSED,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
)
from ray_tpu.tune.result_grid import ResultGrid, TrialResult
from ray_tpu.tune.schedulers.trial_scheduler import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator

logger = logging.getLogger(__name__)

EXPERIMENT_STATE_FILE = "experiment_state.pkl"


@dataclasses.dataclass
class TuneConfig:
    """reference: tune/tune_config.py (metric, mode, num_samples,
    max_concurrent_trials, scheduler)."""

    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Any] = None  # a tune.search.Searcher (suggest mode)
    callbacks: Optional[List[Any]] = None  # extra tune.logger.Callback hooks
    trial_resources: Optional[Dict[str, float]] = None
    seed: Optional[int] = None


class Tuner:
    """reference: tune/tuner.py:43."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        controller = TuneController(
            self._trainable, self._param_space, self._tune_config, self._run_config,
            restore_path=getattr(self, "_restore_path", None),
        )
        return controller.run()

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                *, tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference: Tuner.restore + execution/experiment_state.py).

        Terminated trials keep their results; unfinished trials restart
        from their latest persisted checkpoint.
        """
        if not Tuner.can_restore(path):
            raise ValueError(f"no experiment snapshot under {path!r}")
        if tune_config is None:
            # metric/mode/scheduler travel with the snapshot; peek at JUST
            # the state file (the controller downloads the full experiment —
            # checkpoints included — exactly once, in its restore branch)
            tune_config = TuneController._peek_snapshot(path).get("tune_config")
        run_config = run_config or RunConfig(
            name=os.path.basename(path.rstrip("/")),
            storage_path=os.path.dirname(path.rstrip("/")) or ".")
        tuner = cls(trainable, tune_config=tune_config, run_config=run_config)
        tuner._restore_path = path
        return tuner

    @staticmethod
    def can_restore(path: str) -> bool:
        from ray_tpu.train._internal.checkpoint_util import (
            is_remote_path, join_path)

        if is_remote_path(path):
            import fsspec

            fs, p = fsspec.core.url_to_fs(join_path(path, EXPERIMENT_STATE_FILE))
            return fs.exists(p)
        return os.path.exists(os.path.join(path, EXPERIMENT_STATE_FILE))


class TuneController:
    """reference: tune/execution/tune_controller.py:68."""

    def __init__(self, trainable, param_space, tune_config: TuneConfig,
                 run_config: RunConfig, restore_path: Optional[str] = None):
        self._trainable = trainable
        self._tc = tune_config
        self._rc = run_config
        self._search_alg = tune_config.search_alg
        if self._search_alg is not None:
            self._search_alg.set_search_properties(
                tune_config.metric, tune_config.mode, param_space)
        self._remote_exp_dir: Optional[str] = None
        self._failed_syncs: set = set()
        if restore_path:
            from ray_tpu.train._internal.checkpoint_util import is_remote_path

            if is_remote_path(restore_path):
                self._remote_exp_dir = restore_path.rstrip("/")
            self._exp_dir = self._materialize_exp_dir(restore_path)
            self.trials = self._load_experiment_state(self._exp_dir)
        elif self._search_alg is not None:
            # suggest mode: trials are created on demand in the run loop
            self._exp_dir = self._setup_exp_dir(run_config)
            self.trials = []
        else:
            self._exp_dir = self._setup_exp_dir(run_config)
            gen = BasicVariantGenerator(param_space, tune_config.num_samples,
                                        seed=tune_config.seed)
            self.trials = [Trial(config=cfg) for cfg in gen.variants()]
        self._search_exhausted = self._search_alg is None
        self._scheduler = tune_config.scheduler or FIFOScheduler()
        from ray_tpu.tune.logger import default_callbacks

        # CSV + JSON trial loggers are always on (reference: tune's default
        # logger callbacks); user callbacks run first
        self._callbacks = list(tune_config.callbacks or []) + default_callbacks()
        for t in self.trials:
            self._scheduler.on_trial_add(t)
        self._actors: Dict[str, Any] = {}  # trial_id -> actor handle

    # -- experiment storage setup (local staging + remote sync) --------------
    # Remote (fsspec URI) storage works the reference's way
    # (tune/execution/experiment_state.py:129,253 — sync up/down): the live
    # experiment dir stays LOCAL (atomic renames, trial logger files), and
    # the DRIVER mirrors the state file + persisted trial checkpoints to the
    # remote URI. Driver-side-only fsspec keeps this testable against
    # per-process filesystems (memory://) and matches the reference syncer.

    @staticmethod
    def _staging_root() -> str:
        return os.path.join(os.path.expanduser("~"), ".ray_tpu", "tune_staging")

    def _setup_exp_dir(self, run_config: RunConfig) -> str:
        from ray_tpu.train._internal.checkpoint_util import (
            is_remote_path, join_path, makedirs_any)

        name = run_config.name or f"tune_{uuid.uuid4().hex[:8]}"
        base = run_config.resolved_storage_path()
        if is_remote_path(base):
            self._remote_exp_dir = join_path(base, name)
            makedirs_any(self._remote_exp_dir)
            base = self._staging_root()
        exp_dir = os.path.join(base, name)
        os.makedirs(exp_dir, exist_ok=True)
        return exp_dir

    @staticmethod
    def _materialize_exp_dir(path: str) -> str:
        """Local experiment dir for ``path`` — downloads a remote experiment
        into the staging area (sync-down; reference: experiment_state.py:253)."""
        from ray_tpu.train._internal.checkpoint_util import (
            download_dir, is_remote_path)

        if not is_remote_path(path):
            return path
        local = os.path.join(TuneController._staging_root(),
                             path.rstrip("/").rsplit("/", 1)[-1])
        download_dir(path, local)
        return local

    def _sync_up(self, local_path: str) -> None:
        """Mirror a file/dir under the experiment dir to the remote URI.
        Failures queue the path for retry at the next state save — a
        checkpoint must never be recorded in the remote state file without
        its directory eventually reaching the remote too."""
        if self._remote_exp_dir is None:
            return
        from ray_tpu.train._internal.checkpoint_util import join_path, upload_dir

        rel = os.path.relpath(local_path, self._exp_dir)
        dest = join_path(self._remote_exp_dir, *rel.split(os.sep))
        try:
            if os.path.isdir(local_path):
                upload_dir(local_path, dest)
            else:
                import fsspec

                fs, p = fsspec.core.url_to_fs(dest)
                fs.makedirs(p.rsplit("/", 1)[0], exist_ok=True)
                fs.put(local_path, p)
            self._failed_syncs.discard(local_path)
        except Exception:  # noqa: BLE001
            logger.exception("tune: sync-up of %s failed (queued for retry)", rel)
            self._failed_syncs.add(local_path)

    def _retry_failed_syncs(self) -> None:
        for path in list(getattr(self, "_failed_syncs", ())):
            if os.path.exists(path):
                self._sync_up(path)
            else:
                self._failed_syncs.discard(path)

    # -- experiment snapshot/restore (reference: experiment_state.py) -------

    def _save_experiment_state(self):
        import pickle

        # only rewrite when some trial actually changed state
        signature = tuple((t.trial_id, t.status, t.training_iteration)
                          for t in self.trials)
        if signature == getattr(self, "_last_saved_signature", None):
            return
        rows = []
        for t in self.trials:
            ckpt = t.checkpoint_path
            if ckpt:
                # persist checkpoints relative to the experiment dir so a
                # restore on another machine (remote storage sync-down into a
                # different staging root) resolves them
                try:
                    rel = os.path.relpath(ckpt, self._exp_dir)
                    if not rel.startswith(".."):
                        ckpt = rel
                except ValueError:
                    pass
            rows.append({
                "trial_id": t.trial_id, "config": t.config, "status": t.status,
                "training_iteration": t.training_iteration, "metrics": t.metrics,
                "metrics_history": t.metrics_history, "error": t.error,
                "checkpoint_path": ckpt,
            })
        # the scheduler is live mutable state keyed by Trial OBJECTS — a
        # pickled copy would revive ghost trials on restore; persist the
        # config without it (restore builds a fresh scheduler)
        # (search_alg likewise: live state keyed by trial ids; restore
        # finishes the already-suggested trials instead)
        saved_tc = dataclasses.replace(self._tc, scheduler=None,
                                       search_alg=None, callbacks=None)
        tmp = os.path.join(self._exp_dir, EXPERIMENT_STATE_FILE + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump({"trials": rows, "tune_config": saved_tc}, f)
        state_file = os.path.join(self._exp_dir, EXPERIMENT_STATE_FILE)
        os.replace(tmp, state_file)
        self._last_saved_signature = signature
        self._retry_failed_syncs()  # e.g. a checkpoint whose upload failed
        self._sync_up(state_file)

    @staticmethod
    def _load_experiment_state(path: str) -> List[Trial]:
        rows = TuneController._load_snapshot(path)["trials"]
        trials = []
        for row in rows:
            t = Trial(config=row["config"])
            t.trial_id = row["trial_id"]
            t.training_iteration = row["training_iteration"]
            t.metrics = row["metrics"]
            t.metrics_history = row["metrics_history"]
            ckpt = row["checkpoint_path"]
            if ckpt and not os.path.isabs(ckpt):
                # relative snapshot entries resolve against the (possibly
                # just-downloaded) experiment dir
                ckpt = os.path.join(path, ckpt)
            t.checkpoint_path = ckpt
            if row["status"] == TERMINATED:
                t.status = TERMINATED
                t.error = row["error"]
            else:
                # unfinished trials resume from their last checkpoint with a
                # clean slate — a stale error must not shadow the re-run
                t.status = PENDING
                t.error = None
            trials.append(t)
        return trials

    @staticmethod
    def _load_snapshot(path: str) -> dict:
        import pickle

        with open(os.path.join(path, EXPERIMENT_STATE_FILE), "rb") as f:
            snap = pickle.load(f)
        if isinstance(snap, list):  # pre-tune_config snapshot layout
            snap = {"trials": snap, "tune_config": None}
        return snap

    @staticmethod
    def _peek_snapshot(path: str) -> dict:
        """Read ONLY the experiment state file from a local or remote
        experiment dir — no checkpoint download."""
        import pickle

        from ray_tpu.train._internal.checkpoint_util import (
            is_remote_path, join_path)

        if not is_remote_path(path):
            return TuneController._load_snapshot(path)
        import fsspec

        with fsspec.open(join_path(path, EXPERIMENT_STATE_FILE), "rb") as f:
            snap = pickle.load(f)
        if isinstance(snap, list):
            snap = {"trials": snap, "tune_config": None}
        return snap

    # -- trial actor management --------------------------------------------
    def _start_trial(self, trial: Trial, resume_checkpoint: Optional[str] = None):
        import ray_tpu

        res = dict(self._tc.trial_resources or {"CPU": 1.0})
        cls = ray_tpu.remote(RayTrainWorker).options(
            num_cpus=res.get("CPU", 1.0),
            resources={k: v for k, v in res.items() if k != "CPU"},
            max_concurrency=4,
        )
        actor = cls.remote()
        trial_dir = os.path.join(self._exp_dir, trial.trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        trial.local_dir = trial_dir
        ray_tpu.get(actor._setup_session.remote(
            world_size=1, world_rank=0, run_name=trial.trial_id,
            storage_path=trial_dir,
        ))
        if resume_checkpoint:
            from ray_tpu.train._internal.checkpoint_util import (
                set_session_resume_checkpoint,
            )

            ray_tpu.get(actor._execute.remote(
                set_session_resume_checkpoint, resume_checkpoint))
        ray_tpu.get(actor._start_training.remote(self._trainable, trial.config))
        self._actors[trial.trial_id] = actor
        trial.status = RUNNING

    def _stop_trial(self, trial: Trial, status: str = TERMINATED):
        import ray_tpu

        actor = self._actors.pop(trial.trial_id, None)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001 — already-dead trial actor is the goal
                pass
        trial.status = status
        for cb in self._callbacks:
            try:
                if status == ERROR:
                    cb.on_trial_error(trial.training_iteration, trial)
                elif status == TERMINATED:
                    cb.on_trial_complete(trial.training_iteration, trial)
            except Exception:  # noqa: BLE001
                logger.exception("tune callback failed")

    def _persist_checkpoint(self, trial: Trial, ckpt) -> Optional[str]:
        if ckpt is None:
            return None
        from ray_tpu.train._internal.checkpoint_util import persist_staged_checkpoint

        dest = os.path.join(self._exp_dir, trial.trial_id,
                            f"checkpoint_{trial.training_iteration:06d}")
        persist_staged_checkpoint(ckpt.path, dest)
        trial.checkpoint_path = dest
        self._sync_up(dest)  # mirror to remote experiment storage
        return dest

    # -- the event loop -----------------------------------------------------
    def run(self) -> ResultGrid:
        import ray_tpu

        max_concurrent = self._tc.max_concurrent_trials or 8
        try:
            while True:
                self._pull_suggestions(max_concurrent)
                # start pending trials up to the concurrency cap
                pending = [t for t in self.trials if t.status == PENDING]
                while pending and len(self._actors) < max_concurrent:
                    trial = self._scheduler.choose_trial_to_run(pending)
                    if trial is None:
                        break
                    pending.remove(trial)
                    # restored trials resume from their persisted checkpoint
                    self._start_trial(trial, resume_checkpoint=trial.checkpoint_path)
                # poll running trials
                for trial in [t for t in self.trials if t.status == RUNNING]:
                    actor = self._actors.get(trial.trial_id)
                    if actor is None:
                        continue
                    try:
                        results, finished, err = ray_tpu.get(
                            actor._poll_results.remote(0.05), timeout=30)
                    except Exception as e:  # noqa: BLE001
                        trial.error = str(e)
                        self._stop_trial(trial, ERROR)
                        self._searcher_complete(trial, error=True)
                        continue
                    if err:
                        trial.error = err
                        self._stop_trial(trial, ERROR)
                        self._scheduler.on_trial_complete(trial, trial.metrics)
                        self._searcher_complete(trial, error=True)
                        continue
                    decision = TrialScheduler.CONTINUE
                    for r in results:
                        trial.training_iteration += 1
                        metrics = dict(r["metrics"])
                        metrics.setdefault("training_iteration", trial.training_iteration)
                        trial.metrics = metrics
                        trial.metrics_history.append(metrics)
                        self._persist_checkpoint(trial, r.get("checkpoint"))
                        if self._search_alg is not None:
                            self._search_alg.on_trial_result(trial.trial_id, metrics)
                        for cb in self._callbacks:
                            try:
                                cb.on_trial_result(trial.training_iteration,
                                                   trial, metrics)
                            except Exception:  # noqa: BLE001
                                logger.exception("tune callback failed")
                        decision = self._scheduler.on_trial_result(trial, metrics)
                        if decision != TrialScheduler.CONTINUE:
                            break
                    if decision == TrialScheduler.STOP:
                        self._stop_trial(trial, TERMINATED)
                        self._scheduler.on_trial_complete(trial, trial.metrics)
                        self._searcher_complete(trial, error=False)
                    elif decision == TrialScheduler.PAUSE:
                        # PBT exploit/explore: restart from donor checkpoint
                        self._handle_pbt_exploit(trial)
                    elif finished:
                        self._stop_trial(trial, TERMINATED)
                        self._scheduler.on_trial_complete(trial, trial.metrics)
                        self._searcher_complete(trial, error=False)
                self._save_experiment_state()
                if (self._search_exhausted
                        and not any(t.status in (PENDING, RUNNING, PAUSED)
                                    for t in self.trials)):
                    break
                time.sleep(0.02)
        finally:
            for trial in self.trials:
                if trial.trial_id in self._actors:
                    self._stop_trial(trial, trial.status)
            self._save_experiment_state()
        return self._build_result_grid()

    def _pull_suggestions(self, max_concurrent: int):
        """Suggest mode: materialize trials from the searcher on demand
        (reference: tune_controller + SearchGenerator)."""
        from ray_tpu.tune.search.searcher import Searcher

        if self._search_alg is None or self._search_exhausted:
            return
        while (len(self.trials) < self._tc.num_samples
               and sum(1 for t in self.trials
                       if t.status in (PENDING, RUNNING)) < max_concurrent):
            trial = Trial(config={})
            suggestion = self._search_alg.suggest(trial.trial_id)
            if suggestion == Searcher.FINISHED:
                self._search_exhausted = True
                return
            if suggestion is None:
                # searcher wants to wait for in-flight trials; PAUSED trials
                # (PBT exploit models pauses) hold ConcurrencyLimiter slots
                # and WILL resume, so they count as in flight too
                if not any(t.status in (PENDING, RUNNING, PAUSED) for t in self.trials):
                    logger.warning("searcher returned None with no trials "
                                   "in flight; ending search")
                    self._search_exhausted = True
                return
            trial.config = suggestion
            self.trials.append(trial)
            self._scheduler.on_trial_add(trial)
        if len(self.trials) >= self._tc.num_samples:
            self._search_exhausted = True

    def _searcher_complete(self, trial: Trial, error: bool):
        if self._search_alg is not None:
            try:
                self._search_alg.on_trial_complete(
                    trial.trial_id, trial.metrics, error=error)
            except Exception:  # noqa: BLE001
                logger.exception("search_alg.on_trial_complete failed")

    def _handle_pbt_exploit(self, trial: Trial):
        donor: Optional[Trial] = trial.pbt_exploit_from
        new_config = trial.pbt_new_config or trial.config
        trial.pbt_exploit_from = None
        trial.pbt_new_config = None
        self._stop_trial(trial, PAUSED)
        trial.config = new_config
        ckpt = donor.checkpoint_path if donor is not None else trial.checkpoint_path
        logger.info("PBT exploit: trial %s <- donor %s (ckpt=%s)",
                    trial.trial_id, donor.trial_id if donor else None, ckpt)
        self._start_trial(trial, resume_checkpoint=ckpt)

    def _build_result_grid(self) -> ResultGrid:
        results = []
        for t in self.trials:
            results.append(TrialResult(
                trial_id=t.trial_id,
                config=t.config,
                metrics=t.metrics,
                metrics_history=t.metrics_history,
                error=t.error,
                checkpoint_path=t.checkpoint_path,
                path=os.path.join(self._exp_dir, t.trial_id),
            ))
        return ResultGrid(results, metric=self._tc.metric, mode=self._tc.mode)
