"""User-facing scheduling strategies.

reference: python/ray/util/scheduling_strategies.py
(PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy,
NodeLabelSchedulingStrategy).
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu._private.ids import NodeID
from ray_tpu._private.scheduler import SchedulingStrategy


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group, placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(
            kind="placement_group",
            placement_group_id=self.placement_group.id,
            bundle_index=self.placement_group_bundle_index,
        )


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id if isinstance(node_id, NodeID) else NodeID(node_id)
        self.soft = soft

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="node_affinity", node_id=self.node_id, soft=self.soft)


class NodeLabelSchedulingStrategy:
    def __init__(self, hard: Optional[Dict[str, str]] = None, soft: Optional[Dict[str, str]] = None):
        self.hard = hard or {}
        self.soft = soft or {}

    def to_internal(self) -> SchedulingStrategy:
        return SchedulingStrategy(kind="node_label", labels=self.hard)
