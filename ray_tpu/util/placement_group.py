"""Placement groups: gang resource reservation.

reference: python/ray/util/placement_group.py (strategies :17-20, API
:146-164; 2-phase prepare/commit on raylets node_manager.cc:1761,1777).

TPU extension: ``placement_group(..., tpu_slice="name")`` restricts bundle
placement to hosts of one pod slice (label ``ray.io/tpu-slice-name``), making
the slice the gang-scheduling atom (SURVEY.md hard-part #2).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def ready(self):
        """ObjectRef that resolves to this PG once all bundles are reserved.

        reference parity (python/ray/util/placement_group.py:146-164):
        ``ray_tpu.get(pg.ready())`` blocks until placement succeeds. Use
        :meth:`wait` for the boolean/polling form.
        """
        import ray_tpu

        pg = self

        @ray_tpu.remote(num_cpus=0)
        def _pg_ready():
            if not pg.wait():
                raise RuntimeError(
                    f"placement group {pg.id.hex()} was removed before "
                    "placement completed")
            return pg

        return _pg_ready.remote()

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        deadline = (None if timeout_seconds is None
                    else time.monotonic() + timeout_seconds)
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        while True:
            info = w.gcs.call("GetPlacementGroup", {"pg_id": self.id})
            if info is not None and info["state"] == "CREATED":
                return True
            if info is not None and info["state"] == "REMOVED":
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.02)

    def bundle_nodes(self):
        from ray_tpu._private.worker import get_global_worker

        info = get_global_worker().gcs.call("GetPlacementGroup", {"pg_id": self.id})
        return info["bundle_nodes"] if info else []

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
    lifetime: Optional[str] = None,
    tpu_slice: Optional[str] = None,
) -> PlacementGroup:
    from ray_tpu._private.worker import get_global_worker

    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    w = get_global_worker()
    pg_id = PlacementGroupID.random()
    w.gcs.call(
        "CreatePlacementGroup",
        {
            "pg_id": pg_id,
            "bundles": bundles,
            "strategy": strategy,
            "name": name,
            "lifetime": lifetime,
            "slice_label": tpu_slice,
        },
    )
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    from ray_tpu._private.worker import get_global_worker

    get_global_worker().gcs.call("RemovePlacementGroup", {"pg_id": pg.id})


def get_placement_group(name: str) -> Optional[PlacementGroup]:
    from ray_tpu._private.worker import get_global_worker

    info = get_global_worker().gcs.call("GetNamedPlacementGroup", {"name": name})
    if info is None:
        return None
    return PlacementGroup(info["pg_id"], info["bundles"])
