"""State API: list/summarize live cluster entities.

reference: python/ray/util/state/api.py — list_actors/list_tasks/list_objects/
list_nodes/list_placement_groups/list_jobs/list_workers + summaries; data
sourced from the GCS (actors, nodes, PGs, jobs, task events) and from each
raylet (objects, workers), exactly the reference's GCS + per-node-agent split.

Filters are ``(key, op, value)`` tuples with op in {"=", "!="} — the subset
the reference CLI uses most.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

Filter = Tuple[str, str, Any]


def _apply_filters(rows: List[dict], filters: Optional[Sequence[Filter]]) -> List[dict]:
    if not filters:
        return rows
    out = []
    for r in rows:
        ok = True
        for key, op, value in filters:
            have = r.get(key)
            have_s = have.hex() if hasattr(have, "hex") and not isinstance(have, (str, bytes)) else have
            if op == "=":
                ok = have_s == value or have == value
            elif op == "!=":
                ok = have_s != value and have != value
            else:
                raise ValueError(f"unsupported filter op {op!r} (use '=' or '!=')")
            if not ok:
                break
        if ok:
            out.append(r)
    return out


class StateApiClient:
    """Talks to the GCS of the connected cluster (reference: StateApiClient)."""

    def __init__(self, worker=None):
        if worker is None:
            from ray_tpu._private.worker import get_global_worker

            worker = get_global_worker()
        if worker is None:
            raise RuntimeError("ray_tpu.init() must be called before using the state API")
        self._w = worker

    # -- GCS-backed listings -------------------------------------------

    def list_nodes(self, filters=None, limit: int = 10000) -> List[dict]:
        rows = self._w.gcs.call("GetAllNodeInfo", {}) or []
        return _apply_filters(rows, filters)[:limit]

    def list_cluster_events(self, filters=None, limit: int = 1000,
                            severity: Optional[str] = None,
                            after_id: int = 0) -> List[dict]:
        """reference: dashboard/modules/event/ aggregated cluster events."""
        rows = self._w.gcs.call("ListEvents", {
            "severity": severity, "after_id": after_id, "limit": limit}) or []
        return _apply_filters(rows, filters)[:limit]

    def record_event(self, message: str, *, severity: str = "INFO",
                     source: str = "user", **metadata) -> None:
        """Append a user event to the cluster event log."""
        self._w.gcs.call("RecordEvent", {
            "severity": severity, "source": source, "message": message,
            "metadata": metadata})

    def list_actors(self, filters=None, limit: int = 10000) -> List[dict]:
        rows = self._w.gcs.call("ListActors", {}) or []
        return _apply_filters(rows, filters)[:limit]

    def list_placement_groups(self, filters=None, limit: int = 10000) -> List[dict]:
        rows = self._w.gcs.call("ListPlacementGroups", {}) or []
        return _apply_filters(rows, filters)[:limit]

    def list_jobs(self, filters=None, limit: int = 10000) -> List[dict]:
        rows = self._w.gcs.call("ListJobs", {}) or []
        return _apply_filters(rows, filters)[:limit]

    @staticmethod
    def _fold_task_events(events: List[dict]) -> List[dict]:
        """Latest state per (task_id, attempt), folded from the task-event
        log (reference: GcsTaskManager).  Per-attempt phase timestamps:
        creation (owner SUBMITTED), queued/scheduled (raylet), start
        (executor RUNNING), end (owner FINISHED/FAILED)."""
        folded: Dict[Tuple[str, int], dict] = {}
        for ev in events:
            key = (ev["task_id"], ev.get("attempt", 0))
            row = folded.setdefault(
                key,
                {
                    "task_id": ev["task_id"],
                    "attempt": ev.get("attempt", 0),
                    "name": ev.get("name"),
                    "job_id": ev.get("job_id"),
                    "actor_id": ev.get("actor_id"),
                    "state": None,
                    "creation_time": None,
                    "queued_time": None,
                    "scheduled_time": None,
                    "start_time": None,
                    "end_time": None,
                    "node_id": None,
                    "pid": None,
                    "submit_pid": None,
                    "submit_node_id": None,
                },
            )
            if ev.get("trace_id"):
                row["trace_id"] = ev["trace_id"]
                row["span_id"] = ev.get("span_id")
                row["parent_span_id"] = ev.get("parent_span_id")
            if ev.get("kind"):
                row["kind"] = ev["kind"]
            state, t = ev["state"], ev["time"]
            if state == "SUBMITTED":
                row["creation_time"] = t
                row["submit_pid"] = ev.get("pid")
                row["submit_node_id"] = ev.get("node_id")
            elif state == "QUEUED":
                row["queued_time"] = t
            elif state == "SCHEDULED":
                row["scheduled_time"] = t
            elif state == "RUNNING":
                row["start_time"] = t
                row["node_id"] = ev.get("node_id")
                row["pid"] = ev.get("pid")
                if ev.get("attributes"):
                    row["attributes"] = ev["attributes"]
            elif state in ("FINISHED", "FAILED"):
                row["end_time"] = t
            order = {"SUBMITTED": 0, "QUEUED": 1, "SCHEDULED": 2,
                     "RUNNING": 3, "FINISHED": 4, "FAILED": 4}
            if row["state"] is None or order.get(state, 0) >= order.get(row["state"], 0):
                row["state"] = state
        return sorted(folded.values(),
                      key=lambda r: (r["creation_time"] or r["start_time"] or 0))

    def list_tasks(self, filters=None, limit: int = 10000) -> List[dict]:
        """Latest state per (task_id, attempt), folded from the task-event log
        (reference: GcsTaskManager)."""
        events = self._w.gcs.call("ListTaskEvents", {"limit": 100000}) or []
        rows = self._fold_task_events(events)
        return _apply_filters(rows, filters)[:limit]

    # -- distributed traces (tentpole: util/tracing.py context) ---------

    def get_trace(self, trace_id: str) -> List[dict]:
        """Every span of one trace, folded per (span_id, attempt): task
        spans carry phase timestamps (creation/queued/scheduled/start/end),
        custom spans (tracing.span, collectives, engine phases) carry
        start/end + kind."""
        # flush this process's buffered span events first (like timeline()):
        # a just-closed driver-side span must be queryable immediately
        try:
            self._w.flush_task_events()
        except Exception:  # noqa: BLE001 — flush is best-effort; stale spans still list
            pass
        events = self._w.gcs.call(
            "ListTaskEvents", {"limit": 100000, "trace_id": trace_id}) or []
        rows = self._fold_task_events(events)
        out = []
        for r in rows:
            if not r.get("span_id"):
                continue
            kind = r.get("kind")
            if kind is None:
                kind = "actor_task" if r.get("actor_id") else "task"
            out.append({
                "trace_id": trace_id,
                "span_id": r["span_id"],
                "parent_span_id": r.get("parent_span_id"),
                "name": r.get("name"),
                "kind": kind,
                "attempt": r.get("attempt", 0),
                "task_id": r.get("task_id"),
                "state": r.get("state"),
                "submitted": r.get("creation_time"),
                "queued": r.get("queued_time"),
                "scheduled": r.get("scheduled_time"),
                "start": r.get("start_time"),
                "end": r.get("end_time"),
                "node_id": r.get("node_id"),
                "pid": r.get("pid"),
                # span payloads: collective bytes/world_size, engine
                # active_slots/chunk, data num_rows
                "attributes": r.get("attributes"),
            })
        return out

    @staticmethod
    def _span_begin(s: dict):
        for k in ("submitted", "queued", "scheduled", "start"):
            if s.get(k) is not None:
                return s[k]
        return None

    @staticmethod
    def _span_end(s: dict):
        for k in ("end", "start", "scheduled", "queued", "submitted"):
            if s.get(k) is not None:
                return s[k]
        return None

    def summarize_trace(self, trace_id: str,
                        spans: Optional[List[dict]] = None) -> dict:
        """Critical-path walk of one trace.

        From the root span, repeatedly descend into the latest-ending
        child; a cursor sweeps wall-clock time once, so the per-phase
        attribution (submit rpc / queueing / spawn+dispatch / execution /
        collective) telescopes to exactly the root span's duration —
        "where did this request's time go?".  Pass ``spans`` (a
        ``get_trace`` result) to avoid re-fetching the event log.
        """
        from collections import defaultdict

        if spans is None:
            spans = self.get_trace(trace_id)
        # latest attempt wins per span_id (retries reuse the span)
        by_id: Dict[str, dict] = {}
        for s in spans:
            cur = by_id.get(s["span_id"])
            if cur is None or s["attempt"] >= cur["attempt"]:
                by_id[s["span_id"]] = s
        if not by_id:
            return {"trace_id": trace_id, "num_spans": 0,
                    "wall_clock_s": 0.0, "phases_s": {}, "critical_path": []}
        children = defaultdict(list)
        for s in by_id.values():
            parent = s.get("parent_span_id")
            if parent and parent in by_id:
                children[parent].append(s)
        roots = [s for s in by_id.values()
                 if not s.get("parent_span_id")
                 or s["parent_span_id"] not in by_id]
        root = min(roots, key=lambda s: self._span_begin(s) or float("inf"))
        # partial traces (the bounded event sink can evict a trace's older
        # RUNNING/SUBMITTED events while later ones survive) may leave the
        # root — or every span — with no begin timestamp; anchor the walk
        # at the earliest timestamp present instead of epoch 0
        begins = [b for s in by_id.values()
                  for b in (self._span_begin(s),) if b is not None]
        if not begins:
            return {"trace_id": trace_id, "num_spans": len(by_id),
                    "wall_clock_s": 0.0, "phases_s": {}, "critical_path": [],
                    "partial": True}

        phases: Dict[str, float] = defaultdict(float)

        def bucket_of(s: dict) -> str:
            return "collective" if s.get("kind") == "collective" else "execution"

        # build the latest-ending-child chain ITERATIVELY: a continuation-
        # style trace can nest deeper than the interpreter recursion limit
        path: List[dict] = [root]
        seen = {root["span_id"]}
        while True:
            kids = children.get(path[-1]["span_id"]) or []
            kid = max(kids, key=lambda c: self._span_end(c) or 0.0,
                      default=None)
            if kid is None or kid["span_id"] in seen:
                break
            path.append(kid)
            seen.add(kid["span_id"])

        begin = self._span_begin(root) or min(begins)
        cursor = begin
        # descend: each span's pre-execution phases, with the gap up to a
        # child's begin charged to the PARENT's execution bucket
        for i, s in enumerate(path):
            if i > 0:
                kb = self._span_begin(s)
                if kb is not None and kb > cursor:
                    phases[bucket_of(path[i - 1])] += kb - cursor
                    cursor = kb
            for phase, key in (("submit", "queued"),
                               ("queueing", "scheduled"),
                               ("spawn", "start")):
                t = s.get(key)
                if t is not None and t > cursor:
                    phases[phase] += t - cursor
                    cursor = t
        # ascend: close each span leaf-first, charging the remainder to its
        # own bucket — together the cursor sweeps [begin, finish] exactly
        # once, so the phase sums telescope to the wall clock
        for s in reversed(path):
            e = self._span_end(s)
            if e is not None and e > cursor:
                phases[bucket_of(s)] += e - cursor
                cursor = e
        finish = cursor
        return {
            "trace_id": trace_id,
            "num_spans": len(by_id),
            "wall_clock_s": finish - begin,
            "phases_s": dict(phases),
            "critical_path": [
                {"span_id": s["span_id"], "name": s.get("name"),
                 "kind": s.get("kind"), "task_id": s.get("task_id"),
                 "begin": self._span_begin(s), "end": self._span_end(s),
                 "node_id": s.get("node_id"), "pid": s.get("pid")}
                for s in path
            ],
        }

    # -- raylet-backed listings ----------------------------------------

    def _each_raylet(self, method: str, payload: dict) -> List[dict]:
        out = []
        for node in self.list_nodes():
            if node.get("state") == "DEAD":
                continue
            try:
                reply = self._w.pool.get(tuple(node["address"])).call(method, payload, timeout=5)
            except Exception:  # noqa: BLE001 — unreachable raylet: return the rows we have
                continue
            for row in reply or []:
                row["node_id"] = node["node_id"]
                out.append(row)
        return out

    def list_objects(self, filters=None, limit: int = 10000) -> List[dict]:
        rows = self._each_raylet("ListObjects", {})
        return _apply_filters(rows, filters)[:limit]

    def list_workers(self, filters=None, limit: int = 10000) -> List[dict]:
        rows = self._each_raylet("ListWorkers", {})
        return _apply_filters(rows, filters)[:limit]

    # -- per-node agent endpoints (reference: dashboard reporter) -------

    def node_stats(self) -> List[dict]:
        """CPU/memory/load + per-worker rss for every alive node."""
        out = []
        for node in self._alive_nodes():
            try:
                stats = self._w.pool.get(tuple(node["address"])).call(
                    "AgentNodeStats", {}, timeout=10)
                stats["node_id"] = node["node_id"]
                out.append(stats)
            except Exception:  # noqa: BLE001 — unreachable node: skip its stats
                continue
        return out

    def _alive_nodes(self, node_id=None):
        """Alive nodes, optionally narrowed to one id (NodeID or hex str) —
        the shared filter for every per-node agent endpoint."""
        want = None
        if node_id is not None:
            want = node_id.hex() if hasattr(node_id, "hex") else str(node_id)
        for node in self.list_nodes():
            if node.get("state") == "DEAD":
                continue
            nid = node["node_id"]
            nid_hex = nid.hex() if hasattr(nid, "hex") else str(nid)
            if want is not None and nid_hex != want:
                continue
            yield node

    def node_metrics(self, node_id=None) -> List[dict]:
        """Per-node Prometheus exposition text from each raylet's metrics
        agent endpoint (reference: the per-node MetricsAgent /metrics; the
        head's /metrics is the cluster aggregate)."""
        out = []
        for node in self._alive_nodes(node_id):
            try:
                text = self._w.pool.get(tuple(node["address"])).call(
                    "AgentMetrics", {}, timeout=10)
                out.append({"node_id": node["node_id"], "metrics": text})
            except Exception:  # noqa: BLE001 — unreachable node: skip its metrics
                continue
        return out

    def dump_stacks(self, node_id=None, pid: Optional[int] = None) -> List[dict]:
        """Stack traces from every worker (reference: `ray stack`)."""
        out = []
        for node in self.list_nodes():
            if node.get("state") == "DEAD":
                continue
            if node_id is not None and node["node_id"] != node_id:
                continue
            try:
                reply = self._w.pool.get(tuple(node["address"])).call(
                    "AgentStacks", {"pid": pid}, timeout=30)
            except Exception:  # noqa: BLE001 — unreachable node: skip its stacks
                continue
            for row in reply or []:
                row["node_id"] = node["node_id"]
                out.append(row)
        return out

    def dump_native_stacks(self, pid: int, node_id=None) -> List[dict]:
        """Native (C/XLA) frames of one worker's threads, even when it is
        wedged inside a native call where the Python-level ``dump_stacks``
        shows nothing (reference: reporter agent py-spy integration)."""
        out = []
        for node in self.list_nodes():
            if node.get("state") == "DEAD":
                continue
            if node_id is not None and node["node_id"] != node_id:
                continue
            try:
                reply = self._w.pool.get(tuple(node["address"])).call(
                    "AgentNativeStacks", {"pid": pid}, timeout=30)
            except Exception:  # noqa: BLE001 — unreachable node: skip its native stacks
                continue
            if reply:
                reply["node_id"] = node["node_id"]
                out.append(reply)
        return out

    def flight_recorder(self, node_id=None, pid: Optional[int] = None,
                        seconds: Optional[float] = None,
                        limit: Optional[int] = 200) -> List[dict]:
        """Flight-recorder tails from every (or one) node: per process, the
        last seconds of step phases, collective entry/exit marks, task and
        lease transitions.  Dead workers come back as their crash-dump
        contents (the `<pid>.flight` file written next to the native stack
        dump)."""
        out = []
        for node in self._alive_nodes(node_id):
            try:
                reply = self._w.pool.get(tuple(node["address"])).call(
                    "AgentFlightRecorder",
                    {"pid": pid, "seconds": seconds, "limit": limit},
                    timeout=15)
            except Exception:  # noqa: BLE001 — unreachable node: skip its recorder tail
                continue
            for row in reply or []:
                row["node_id"] = node["node_id"]
                out.append(row)
        return out

    # -- hang & straggler diagnosis (tentpole) -------------------------

    def diagnose(self, hang_timeout_s: Optional[float] = None,
                 include_stacks: bool = True,
                 source: str = "api") -> dict:
        """One cluster-wide hang sweep: "why is my job stuck right now?"

        Folds three sources into one report:
          1. the collective store's arrival monitor — pending rounds whose
             missing ranks have kept the group waiting past
             ``hang_detect_timeout_s`` name the blocking member (rank +
             actor + node, identity captured at join), the op, and the seq
             it never entered; completed-round arrival-lag EWMAs are the
             persistent-straggler scores;
          2. every process's flight-recorder tail (what each worker was
             doing in the last seconds; entries recorded under a tracing
             context carry trace_ids, cross-linking to state.get_trace());
          3. stack dumps of the blocking workers (python-level; callers can
             follow up with dump_native_stacks/cpu_profile for wedged ones).

        A healthy cluster returns ``hung=False`` with empty ``blocking`` —
        pending rounds younger than the timeout are listed under
        ``pending_young`` but never flagged.
        """
        from ray_tpu._private import runtime_metrics
        from ray_tpu._private.config import global_config

        if hang_timeout_s is None:
            hang_timeout_s = global_config().hang_detect_timeout_s
        runtime_metrics.inc_hang_sweep(source)
        report: dict = {
            "time": time.time(),
            "hang_timeout_s": hang_timeout_s,
            "hung": False,
            "blocking": [],
            "pending_young": [],
            "stragglers": {},
            "aborted_groups": {},
            "trace_ids": [],
        }

        # -- 1. collective arrival monitor --------------------------------
        store_rep = None
        try:
            import ray_tpu
            from ray_tpu.util.collective.store import STORE_ACTOR_NAME

            store = ray_tpu.get_actor(STORE_ACTOR_NAME)
            store_rep = ray_tpu.get(store.straggler_report.remote(),
                                    timeout=15)
        except Exception:  # noqa: BLE001 — no store actor = no collectives
            pass

        # actor -> (node, pid) so a blocking member is named as a process,
        # not just a rank
        actor_nodes: Dict[str, str] = {}
        actor_pids: Dict[str, Optional[int]] = {}
        if store_rep and any(g.get("pending") or g.get("members")
                             for g in store_rep["groups"].values()):
            for a in self.list_actors():
                aid = a.get("actor_id")
                aid = aid.hex() if hasattr(aid, "hex") else str(aid)
                nid = a.get("node_id")
                if nid is not None:
                    actor_nodes[aid] = (nid.hex() if hasattr(nid, "hex")
                                        else str(nid))
            for wrow in self.list_workers():
                if wrow.get("actor_id"):
                    actor_pids[wrow["actor_id"]] = wrow.get("pid")

        if store_rep:
            for group, g in store_rep["groups"].items():
                if g.get("lag_ewma_s"):
                    report["stragglers"][group] = g["lag_ewma_s"]
                if g.get("aborted"):
                    report["aborted_groups"][group] = g["aborted"]
                members = g.get("members") or {}
                for round_ in g.get("pending") or []:
                    rows = []
                    for rank in round_.get("missing") or []:
                        m = members.get(rank) or members.get(str(rank)) or {}
                        aid = m.get("actor_id")
                        rows.append({
                            "group": group,
                            "op": round_["op"],
                            "seq": round_["seq"],
                            "rank": rank,
                            "actor_id": aid,
                            "node_id": m.get("node_id")
                            or actor_nodes.get(aid),
                            "pid": actor_pids.get(aid),
                            "waiting_s": round_["waiting_s"],
                        })
                    if round_["waiting_s"] >= hang_timeout_s and rows:
                        report["blocking"].extend(rows)
                    else:
                        report["pending_young"].append(
                            {"group": group, **round_})
        if report["blocking"]:
            report["hung"] = True

        # -- 2. flight-recorder tails (every process's last seconds) ------
        tails = self.flight_recorder(seconds=max(hang_timeout_s * 2, 30.0),
                                     limit=100)
        report["flight_recorder"] = tails
        trace_ids: List[str] = []
        for row in tails:
            for e in row.get("entries") or []:
                tid = e.get("trace_id")
                if tid and tid not in trace_ids:
                    trace_ids.append(tid)
        report["trace_ids"] = trace_ids[-16:]

        # -- 3. stacks of the blocking workers ----------------------------
        if include_stacks and report["blocking"]:
            stacks = []
            for b in report["blocking"]:
                if b.get("pid") is None:
                    continue
                try:
                    stacks.extend(self.dump_stacks(pid=b["pid"]))
                except Exception:  # noqa: BLE001 — stack dump is enrichment; the report stands without it
                    continue
            report["stacks"] = stacks

        # -- compile watch: storm detector (device telemetry) -------------
        # N traces/compiles of one program inside the storm window name
        # the program and its callers — a shape-churn workload surfaces
        # here before it surfaces as missing throughput
        from ray_tpu._private import device_telemetry

        report["compile_storm"] = device_telemetry.storm_report()

        # -- 4. lock-order witness (test/chaos lanes) ---------------------
        # when RAY_TPU_lock_witness_enabled=1 the driver's own witnessed
        # locks have been building the acquired-while-holding graph; any
        # recorded cycle (with both acquisition stacks) rides the hang
        # report, so an inversion surfaces the same way a hang does
        from ray_tpu._private.analysis import lock_witness

        lw = lock_witness.report()
        if lw.get("enabled"):
            report["lock_witness"] = lw
            if lw.get("cycles"):
                report["hung"] = True
        return report

    # -- goodput ledger (train controller wall-clock accounting) --------

    def goodput(self, run: Optional[str] = None) -> dict:
        """Published goodput ledgers: per run, wall-clock split into
        productive_step / checkpoint / restore / preemption_recovery /
        input_wait / stall buckets (summing exactly to the wall) plus the
        derived goodput ratio.  ``run`` narrows to one run name; also
        accepts a job id recorded in the ledger."""
        from ray_tpu.train._internal.goodput import GOODPUT_KV_PREFIX

        out: Dict[str, dict] = {}
        keys = self._w.gcs.call(
            "KVKeys", {"prefix": GOODPUT_KV_PREFIX}) or []
        for k in keys:
            blob = self._w.gcs.call("KVGet", {"key": k})
            if not blob:
                continue
            try:
                import json

                snap = json.loads(blob)
            except Exception:  # noqa: BLE001 — malformed snapshot row: skip it
                continue
            name = k[len(GOODPUT_KV_PREFIX):]
            if run is not None and run not in (name, snap.get("job_id")):
                continue
            out[name] = snap
        return out

    # -- serving SLO layer (request-level ledger + burn-rate monitoring) --

    def _slo_rows(self) -> list:
        """Fetch every process's published ``slo:*`` snapshot row."""
        import json

        from ray_tpu.serve._private.slo import SLO_KV_PREFIX

        rows = []
        keys = self._w.gcs.call("KVKeys", {"prefix": SLO_KV_PREFIX}) or []
        blobs = self._w.gcs.call("KVMultiGet", {"keys": keys}) or {}
        for blob in blobs.values():
            if not blob:
                continue
            try:
                rows.append(json.loads(blob))
            except Exception:  # noqa: BLE001 — one bad row, not all
                continue
        return rows

    def serving_slo(self, deployment: Optional[str] = None) -> dict:
        """Cluster-wide serving SLO report: per deployment, TTFT/ITL
        percentiles (lossless sketch merge across every ingress — the p99
        is the TRUE p99 of the combined request stream), split by tenant,
        per-stage percentiles (queue_wait/prefill/handoff/decode), terminal
        status counts, effective SLO targets, and multi-window (5m/1h)
        burn rates with the breach list ranked worst-first.  A single slow
        replica shows up here as the deployment's burn rate crossing the
        alert threshold."""
        import json

        from ray_tpu.serve._private import slo as slo_mod

        conf_rows = {}
        try:
            keys = self._w.gcs.call(
                "KVKeys", {"prefix": slo_mod.SLO_CONF_KV_PREFIX}) or []
            blobs = self._w.gcs.call("KVMultiGet", {"keys": keys}) or {}
            for key, blob in blobs.items():
                try:
                    conf_rows[key[len(slo_mod.SLO_CONF_KV_PREFIX):]] = (
                        json.loads(blob))
                except Exception:  # noqa: BLE001 — malformed SLO conf row: skip it
                    continue
        except Exception:  # noqa: BLE001 — defaults still apply
            pass
        report = slo_mod.fold_rows(self._slo_rows(), conf_rows=conf_rows)
        if deployment is not None:
            report["deployments"] = {
                k: v for k, v in report["deployments"].items()
                if k == deployment}
            report["breaches"] = [b for b in report["breaches"]
                                  if b["deployment"] == deployment]
        return report

    def recent_requests(self, limit: int = 100,
                        deployment: Optional[str] = None,
                        tenant: Optional[str] = None) -> List[dict]:
        """Overload forensics: the newest completed requests cluster-wide
        (tenant, status, route reason, TTFT, mean/max ITL, duration,
        trace_id cross-link), folded from every ingress's recent ring."""
        from ray_tpu.serve._private import slo as slo_mod

        rows = slo_mod.fold_recent(self._slo_rows(), limit=limit * 4)
        if deployment is not None:
            rows = [r for r in rows if r.get("deployment") == deployment]
        if tenant is not None:
            rows = [r for r in rows if r.get("tenant") == tenant]
        return rows[-limit:]

    # -- device telemetry (chip-level observability) --------------------

    def utilization(self, deployment: Optional[str] = None) -> dict:
        """Cluster utilization snapshot (device telemetry): per
        deployment, every replica's free decode slots, free KV blocks,
        duty cycle, and HBM split, plus summed headroom — free slots and
        free blocks per deployment are THE SLO-feedback autoscaler's
        inputs (ROADMAP item 1).  Folds GCS-published replica rows
        (serve/_private/replica.py utilization loop) with this process's
        locally registered engines (local-testing-mode serve apps and
        engine-direct benches publish nowhere, but still fold here)."""
        import json

        from ray_tpu._private import device_telemetry

        rows: List[dict] = []
        try:
            keys = self._w.gcs.call(
                "KVKeys",
                {"prefix": device_telemetry.UTIL_KV_PREFIX}) or []
            blobs = self._w.gcs.call("KVMultiGet", {"keys": keys}) or {}
            for blob in blobs.values():
                if not blob:
                    continue
                try:
                    rows.append(json.loads(blob))
                except Exception:  # noqa: BLE001 — one bad row, not all
                    continue
        except Exception:  # noqa: BLE001 — KV unreachable: local rows only
            pass
        rows.extend(device_telemetry.local_utilization_rows())
        snap = device_telemetry.fold_utilization_rows(rows)
        if deployment is not None:
            snap["deployments"] = {
                k: v for k, v in snap["deployments"].items()
                if k == deployment}
        return snap

    # -- metrics history + watch alerts (_private/metrics_history.py) --

    def metric_history(self, family: Optional[str] = None,
                       tags: Optional[dict] = None,
                       window_s: Optional[float] = None,
                       step_s: Optional[float] = None,
                       op: Optional[str] = None,
                       q: float = 0.99) -> dict:
        """Trailing time-series of the cluster metric aggregate, straight
        from the in-GCS history store: per matching (family, tagset) a
        two-resolution sample list (counters as per-bucket deltas — never
        negative across restarts/evictions; gauges last-wins; sketches as
        per-bucket delta sketches whose window merge is lossless).  With
        ``op`` one of rate / delta / avg_over_time / quantile_over_time
        (``q`` sets the quantile) the GCS also evaluates the operator per
        series.  No ``family`` lists the retained families + store
        stats."""
        req: dict = {"family": family, "tags": tags, "window_s": window_s,
                     "step_s": step_s}
        if op:
            req["op"] = op
            req["q"] = q
        return self._w.gcs.call("MetricHistory", req) or {}

    def alerts(self, rule: Optional[str] = None) -> dict:
        """Watch-engine state: active alerts (pending/firing/clearing,
        firing first), the installed rule definitions, and the recent
        firing/cleared transition log.  ``rule`` filters to one rule."""
        return self._w.gcs.call("ListAlerts", {"rule": rule}) or {}

    def add_watch_rule(self, rule: dict) -> bool:
        """Install (or replace, by name) a declarative watch rule — the
        same contract the built-in pack uses; see
        metrics_history.WatchRule for the field grammar."""
        return bool(self._w.gcs.call("AddWatchRule", {"rule": rule}))

    def remove_watch_rule(self, name: str) -> bool:
        return bool(self._w.gcs.call("RemoveWatchRule", {"name": name}))

    def profile(self, pid: int, node_id=None, duration_s: float = 2.0,
                mode: str = "auto") -> dict:
        """On-demand profiler capture of one worker (device telemetry):
        a jax.profiler XPlane trace where the target's backend supports
        it, else the pure-Python sampling profile (sys._current_frames
        over the worker RPC thread, like PR 6's FlightRecorderTail).
        Returns the artifact path plus the trace_ids active on the
        worker around the capture window (flight-recorder tail), so a
        chip-level capture cross-links to ``state.get_trace()``."""
        if mode not in ("auto", "jax", "cpu"):
            raise ValueError(f"mode must be auto|jax|cpu (got {mode!r})")
        result: dict = {"pid": pid, "mode": None, "artifact": None}
        if mode in ("auto", "jax"):
            try:
                rep = self.jax_profile(pid, node_id=node_id,
                                       duration_s=duration_s)
                files = rep.get("files") or []
                if files or mode == "jax":
                    result["mode"] = "jax"
                    result["artifact"] = files[0] if files \
                        else rep.get("logdir")
                    result["logdir"] = rep.get("logdir")
                    result["files"] = files
            except Exception:  # noqa: BLE001 — fall back to sampling
                if mode == "jax":
                    raise
        if result["mode"] is None:
            import json
            import os
            import tempfile

            rep = self.cpu_profile(pid, node_id=node_id,
                                   duration_s=duration_s)
            fd, path = tempfile.mkstemp(
                prefix=f"ray_tpu_profile_{pid}_", suffix=".json")
            with os.fdopen(fd, "w") as f:
                json.dump(rep, f, indent=1)
            result["mode"] = "cpu"
            result["artifact"] = path
            result["samples"] = rep.get("samples")
        try:
            tids: List[str] = []
            for row in self.flight_recorder(pid=pid,
                                            seconds=duration_s + 30):
                for e in row.get("entries") or []:
                    t = e.get("trace_id")
                    if t and t not in tids:
                        tids.append(t)
            result["trace_ids"] = tids[-16:]
        except Exception:  # noqa: BLE001 — cross-link is enrichment only
            result["trace_ids"] = []
        return result

    def _agent_call_by_pid(self, method: str, payload: dict, *, pid,
                           node_id, timeout: float) -> dict:
        """Try every live node's agent endpoint for ``pid``; the hosting
        node's real error must never be overwritten by other nodes'
        'no worker with pid' noise."""
        last_error: Optional[Exception] = None
        for node in self.list_nodes():
            if node.get("state") == "DEAD":
                continue
            if node_id is not None and node["node_id"] != node_id:
                continue
            try:
                return self._w.pool.get(tuple(node["address"])).call(
                    method, payload, timeout=timeout)
            except Exception as e:  # noqa: BLE001
                if last_error is None or "no worker with pid" in str(last_error):
                    last_error = e
        raise ValueError(
            f"no worker with pid {pid} found on any node"
            + (f" (last error: {last_error})" if last_error else ""))

    def cpu_profile(self, pid: int, node_id=None, duration_s: float = 5.0) -> dict:
        """Sampling CPU profile of one worker (reference: reporter's
        profiling endpoint)."""
        return self._agent_call_by_pid(
            "AgentProfile", {"pid": pid, "duration_s": duration_s},
            pid=pid, node_id=node_id, timeout=duration_s + 30)

    def jax_profile(self, pid: int, node_id=None, duration_s: float = 3.0,
                    logdir: Optional[str] = None) -> dict:
        """Capture a JAX profiler (XPlane) trace on one worker; open the
        returned logdir with TensorBoard/xprof (SURVEY §5: the TPU analog of
        the reference's GPU profiler plugins)."""
        return self._agent_call_by_pid(
            "AgentJaxProfile",
            {"pid": pid, "duration_s": duration_s, "logdir": logdir},
            pid=pid, node_id=node_id, timeout=duration_s + 60)

    # -- summaries ------------------------------------------------------

    def summarize_tasks(self) -> Dict[str, Dict[str, int]]:
        """Per-function-name count by state (reference: `ray summary tasks`)."""
        summary: Dict[str, Dict[str, int]] = {}
        for t in self.list_tasks(limit=100000):
            by_state = summary.setdefault(t["name"] or "?", {})
            by_state[t["state"]] = by_state.get(t["state"], 0) + 1
        return summary

    def summarize_actors(self) -> Dict[str, Dict[str, int]]:
        summary: Dict[str, Dict[str, int]] = {}
        for a in self.list_actors(limit=100000):
            by_state = summary.setdefault(a.get("class_name") or "?", {})
            by_state[a["state"]] = by_state.get(a["state"], 0) + 1
        return summary


def _client() -> StateApiClient:
    return StateApiClient()


def list_nodes(filters=None, limit: int = 10000):
    return _client().list_nodes(filters, limit)


def list_actors(filters=None, limit: int = 10000):
    return _client().list_actors(filters, limit)


def list_tasks(filters=None, limit: int = 10000):
    return _client().list_tasks(filters, limit)


def get_trace(trace_id: str):
    return _client().get_trace(trace_id)


def summarize_trace(trace_id: str):
    return _client().summarize_trace(trace_id)


def list_objects(filters=None, limit: int = 10000):
    return _client().list_objects(filters, limit)


def list_placement_groups(filters=None, limit: int = 10000):
    return _client().list_placement_groups(filters, limit)


def list_jobs(filters=None, limit: int = 10000):
    return _client().list_jobs(filters, limit)


def list_workers(filters=None, limit: int = 10000):
    return _client().list_workers(filters, limit)


def summarize_tasks():
    return _client().summarize_tasks()


def list_cluster_events(filters=None, limit: int = 1000, severity=None,
                        after_id: int = 0):
    return _client().list_cluster_events(filters, limit, severity, after_id)


def record_event(message: str, *, severity: str = "INFO", source: str = "user",
                 **metadata):
    return _client().record_event(message, severity=severity, source=source,
                                  **metadata)


def summarize_actors():
    return _client().summarize_actors()


def node_stats():
    return _client().node_stats()


def node_metrics(node_id=None):
    return _client().node_metrics(node_id)


def dump_stacks(node_id=None, pid=None):
    return _client().dump_stacks(node_id, pid)


def flight_recorder(node_id=None, pid=None, seconds=None, limit=200):
    return _client().flight_recorder(node_id, pid, seconds, limit)


def diagnose(hang_timeout_s=None, include_stacks: bool = True,
             source: str = "api"):
    return _client().diagnose(hang_timeout_s, include_stacks, source)


def goodput(run=None):
    return _client().goodput(run)


def serving_slo(deployment=None):
    return _client().serving_slo(deployment)


def metric_history(family=None, tags=None, window_s=None, step_s=None,
                   op=None, q: float = 0.99):
    return _client().metric_history(family, tags, window_s, step_s, op, q)


def alerts(rule=None):
    return _client().alerts(rule)


def add_watch_rule(rule: dict):
    return _client().add_watch_rule(rule)


def remove_watch_rule(name: str):
    return _client().remove_watch_rule(name)


def recent_requests(limit: int = 100, deployment=None, tenant=None):
    return _client().recent_requests(limit, deployment, tenant)


def dump_native_stacks(pid, node_id=None):
    return _client().dump_native_stacks(pid, node_id)


def cpu_profile(pid, node_id=None, duration_s: float = 5.0):
    return _client().cpu_profile(pid, node_id, duration_s)


def jax_profile(pid, node_id=None, duration_s: float = 3.0, logdir=None):
    return _client().jax_profile(pid, node_id, duration_s, logdir)


def utilization(deployment=None):
    try:
        client = _client()
    except RuntimeError:
        # no cluster connection: fold this process's registered engines
        # (local-testing-mode serve apps, engine-direct benches)
        from ray_tpu._private import device_telemetry

        snap = device_telemetry.local_utilization()
        if deployment is not None:
            snap["deployments"] = {
                k: v for k, v in snap["deployments"].items()
                if k == deployment}
        return snap
    return client.utilization(deployment)


def profile(pid, node_id=None, duration_s: float = 2.0,
            mode: str = "auto"):
    return _client().profile(pid, node_id, duration_s, mode)


def ingress() -> dict:
    """Ingress control-plane view: this process's admission gate
    (weights, per-tenant inflight), the local scale-out tier (backends,
    live splices) and — when a serve controller is reachable — the pool
    autoscaler's pools and recent actuations.  Reads only state that
    already exists; never constructs the admission singleton."""
    from ray_tpu.serve._private import admission as adm
    from ray_tpu.serve._private import ingress as ing

    out: dict = {"admission": None, "tier": None, "pool_autoscaler": None}
    gate = adm._controller
    if gate is not None:
        out["admission"] = gate.snapshot()
    tier = ing.get_tier()
    if tier is not None:
        out["tier"] = {"address": list(tier.address),
                       "backends": [list(b) for b in tier.backends()],
                       "connections": tier._conns}
    try:
        import ray_tpu
        from ray_tpu.serve._private.controller import get_controller_if_exists

        ctrl = get_controller_if_exists()
        if ctrl is not None:
            out["pool_autoscaler"] = ray_tpu.get(
                ctrl.pool_autoscaler_report.remote())
    except Exception:  # noqa: BLE001 — no controller: local view only
        pass
    return out
