"""Cluster state introspection API.

reference: python/ray/util/state/api.py (+ state_cli.py) — `ray list
actors/tasks/objects/nodes/...` backed by GCS + per-node agents.
"""

from ray_tpu.util.state.api import (
    StateApiClient,
    cpu_profile,
    diagnose,
    flight_recorder,
    goodput,
    jax_profile,
    dump_native_stacks,
    dump_stacks,
    get_trace,
    node_metrics,
    node_stats,
    list_actors,
    list_cluster_events,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    record_event,
    summarize_actors,
    summarize_tasks,
    summarize_trace,
)

__all__ = [
    "StateApiClient",
    "node_metrics",
    "node_stats",
    "diagnose",
    "flight_recorder",
    "goodput",
    "dump_native_stacks",
    "dump_stacks",
    "cpu_profile",
    "jax_profile",
    "list_actors",
    "list_cluster_events",
    "list_jobs",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "list_workers",
    "record_event",
    "summarize_actors",
    "summarize_tasks",
    "get_trace",
    "summarize_trace",
]
