"""multiprocessing.Pool API over the task runtime.

reference: python/ray/util/multiprocessing/ — drop-in Pool whose workers
are actors, so pools span the whole cluster instead of one machine.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional


class AsyncResult:
    """reference: multiprocessing.pool.AsyncResult."""

    def __init__(self, refs):
        self._refs = refs

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        return ray_tpu.get(self._refs, timeout=timeout)

    def wait(self, timeout: Optional[float] = None):
        import ray_tpu

        refs = self._refs if isinstance(self._refs, list) else [self._refs]
        ray_tpu.wait(refs, num_returns=len(refs), timeout=timeout)

    def ready(self) -> bool:
        import ray_tpu

        refs = self._refs if isinstance(self._refs, list) else [self._refs]
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        return len(done) == len(refs)

    def successful(self) -> bool:
        """stdlib semantics: ValueError while the result is not ready."""
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class _CallbackResult(AsyncResult):
    """AsyncResult honoring the stdlib contract: the callback completes
    BEFORE the result reads as ready, and one shared handler thread serves
    every callback (stdlib Pool's _handle_results analog)."""

    def __init__(self, refs):
        super().__init__(refs)
        import threading

        self._event = threading.Event()
        self._value = None
        self._error: Optional[Exception] = None

    def _resolve(self, callback, error_callback):
        try:
            self._value = super().get()
            if callback is not None:
                callback(self._value)
        except Exception as e:  # noqa: BLE001
            self._error = e
            if error_callback is not None:
                try:
                    error_callback(e)
                except Exception:  # noqa: BLE001 — user error_callback raised; the original error is kept
                    pass
        finally:
            self._event.set()

    def get(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            import ray_tpu

            raise ray_tpu.GetTimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout: Optional[float] = None):
        self._event.wait(timeout)

    def ready(self) -> bool:
        return self._event.is_set()


class _PoolWorker:
    def run(self, fn, args):
        return fn(*args)

    def run_batch(self, fn, chunk):
        return [fn(*args) for args in chunk]


class Pool:
    """reference: ray.util.multiprocessing.Pool — actor-backed pool."""

    def __init__(self, processes: Optional[int] = None,
                 ray_remote_args: Optional[dict] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(int(ray_tpu.cluster_resources().get("CPU", 2)), 1)
        self._size = processes
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        cls = ray_tpu.remote(_PoolWorker).options(**opts)
        self._actors = [cls.remote() for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        import threading

        self._cb_lock = threading.Lock()

    # -- submission ----------------------------------------------------

    def _callback_queue(self):
        """One shared handler thread per pool drains every callback in
        submission order (stdlib Pool _handle_results analog)."""
        with self._cb_lock:
            if getattr(self, "_cb_queue", None) is None:
                import queue
                import threading

                q = queue.Queue()
                self._cb_queue = q

                def drain(q=q):  # bound locally: terminate() nulls the attr
                    while True:
                        item = q.get()
                        if item is None:
                            return
                        result, callback, error_callback = item
                        result._resolve(callback, error_callback)

                self._cb_thread = threading.Thread(
                    target=drain, daemon=True, name="pool-callbacks")
                self._cb_thread.start()
            return self._cb_queue

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _normalize_args(self, args):
        return args if isinstance(args, tuple) else (args,)

    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None) -> AsyncResult:
        self._check_open()
        if kwds:
            import functools

            fn = functools.partial(fn, **kwds)
        actor = self._actors[next(self._rr)]
        ref = actor.run.remote(fn, tuple(args))
        if callback is None and error_callback is None:
            return AsyncResult(ref)
        result = _CallbackResult(ref)
        self._callback_queue().put((result, callback, error_callback))
        return result

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        return self.starmap_async(fn, [(x,) for x in iterable], chunksize)

    def starmap(self, fn: Callable, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn: Callable, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None) -> "_MapResult":
        self._check_open()
        items = [tuple(args) for args in iterable]
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * 4) or 1)
        chunks = [items[i:i + chunksize] for i in range(0, len(items), chunksize)]
        refs = [self._actors[next(self._rr)].run_batch.remote(fn, chunk)
                for chunk in chunks]
        return _MapResult(refs)

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        """Ordered iteration; work is submitted EAGERLY (reference Pool
        semantics — results stream as you iterate)."""
        import ray_tpu

        self._check_open()
        items = [(x,) for x in iterable]
        chunks = [items[i:i + chunksize] for i in range(0, len(items), chunksize)]
        refs = [self._actors[next(self._rr)].run_batch.remote(fn, chunk)
                for chunk in chunks]

        def _iter():
            for ref in refs:
                yield from ray_tpu.get(ref)

        return _iter()

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        import ray_tpu

        self._check_open()
        items = [(x,) for x in iterable]
        chunks = [items[i:i + chunksize] for i in range(0, len(items), chunksize)]
        pending = [self._actors[next(self._rr)].run_batch.remote(fn, chunk)
                   for chunk in chunks]

        def _iter():
            nonlocal pending
            while pending:
                done, pending = ray_tpu.wait(pending, num_returns=1)
                for ref in done:
                    yield from ray_tpu.get(ref)

        return _iter()

    # -- lifecycle ------------------------------------------------------

    def close(self):
        self._closed = True

    def terminate(self):
        import ray_tpu

        self._closed = True
        if getattr(self, "_cb_queue", None) is not None:
            self._cb_queue.put(None)  # stop the callback handler thread
            self._cb_queue = None
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — already-dead actor is the goal
                pass
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("join() before close()")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _MapResult(AsyncResult):
    """Flattens chunked results."""

    def get(self, timeout: Optional[float] = None):
        import ray_tpu

        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return [x for chunk in chunks for x in chunk]
