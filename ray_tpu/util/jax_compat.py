"""jax version-compat helpers shared across the framework."""

from __future__ import annotations

import jax


def shard_map(f, **kw):
    """jax.shard_map moved out of jax.experimental across versions; one
    resolution point for every caller (collective backends, benchmarks)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, **kw)
