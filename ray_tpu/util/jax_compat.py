"""jax version-compat helpers shared across the framework."""

from __future__ import annotations

import jax


def shard_map(f, **kw):
    """jax.shard_map moved out of jax.experimental across versions; one
    resolution point for every caller (collective backends, models,
    benchmarks).

    The new API's ``axis_names={...}`` (partial-manual: only the named axes
    are manual inside the body) is translated for the old experimental API
    into its ``auto=`` complement (every OTHER mesh axis stays automatic) —
    this is what lets the context-parallel and pipeline paths run on jax
    0.4.x images."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    axis_names = kw.pop("axis_names", None)
    if axis_names is not None:
        mesh = kw.get("mesh")
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        # axis_names call sites target the new typed-replication (vma)
        # checker; the old check_rep pass lacks rules for several
        # primitives they use (checkpoint_name, ppermute carries), so it
        # must be off for the translation to be usable
        kw.setdefault("check_rep", False)
    return _sm(f, **kw)


def axis_size(axis_name) -> int:
    """lax.axis_size compat: old jax constant-folds ``psum(1, axis)`` to the
    static axis size (the pre-axis_size idiom), so both paths return an int
    usable for Python-level loop bounds."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pcast(x, axes, to="varying"):
    """lax.pcast (typed-replication cast, jax >= 0.6) compat: the old
    shard_map has no varying-manual-axes typing, so the cast is simply the
    identity there."""
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to=to)
    return x
