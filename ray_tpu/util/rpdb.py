"""Remote pdb for worker processes.

reference: python/ray/util/rpdb.py — `ray_tpu.util.rpdb.set_trace()` inside
a task/actor opens a TCP-served pdb, announces it in the GCS KV, and blocks
until a client attaches; `python -m ray_tpu debug` lists open breakpoints
and connects.  Post-mortem: set RAY_TPU_POST_MORTEM=1 and any task raising
an exception drops into a remote pdb at the crash frame.
"""

from __future__ import annotations

import os
import pdb
import socket
import sys
import uuid
from typing import List, Optional


class _SocketIO:
    """File-like stdin/stdout over one accepted connection."""

    def __init__(self, conn: socket.socket):
        self._r = conn.makefile("r")
        self._w = conn.makefile("w")

    def readline(self):
        return self._r.readline()

    def write(self, data):
        self._w.write(data)
        return len(data)

    def flush(self):
        self._w.flush()


def _default_bind_host() -> str:
    """Bind where this worker is reachable from other nodes: the address the
    worker's own RPC server advertises (loopback only for local clusters)."""
    try:
        from ray_tpu._private.worker import _global_worker

        if _global_worker is not None:
            return _global_worker.server.address[0]
    except Exception:  # noqa: BLE001 — no worker yet: loopback is the right default
        pass
    return "127.0.0.1"


class RemotePdb(pdb.Pdb):
    def __init__(self, host: Optional[str] = None, port: int = 0,
                 quiet: bool = False):
        host = host or _default_bind_host()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1)
        self.address = self._listener.getsockname()
        self._quiet = quiet
        self._conn: Optional[socket.socket] = None
        self._key: Optional[str] = None

    def _announce(self, label: str):
        """Record the open breakpoint in the GCS KV so `ray_tpu debug` can
        find it (reference: ray debug's KV-registered active breakpoints)."""
        try:
            from ray_tpu._private.worker import _global_worker

            if _global_worker is None:
                return
            self._key = f"debug:{uuid.uuid4().hex[:12]}"
            _global_worker.gcs.call("KVPut", {
                "key": self._key,
                "value": {"host": self.address[0], "port": self.address[1],
                          "pid": os.getpid(), "label": label},
                "overwrite": True,
            }, timeout=5)
        except Exception:  # noqa: BLE001
            self._key = None

    def _withdraw(self):
        if self._key is None:
            return
        try:
            from ray_tpu._private.worker import _global_worker

            _global_worker.gcs.call("KVDel", {"key": self._key}, timeout=5)
        except Exception:  # noqa: BLE001 — GCS gone: the session key dies with it
            pass

    def _accept(self, label: str):
        if not self._quiet:
            print(f"RemotePdb [{label}] waiting for client at "
                  f"{self.address[0]}:{self.address[1]} "
                  f"(connect: python -m ray_tpu debug)",
                  file=sys.stderr, flush=True)
        self._announce(label)
        conn, _ = self._listener.accept()
        self._conn = conn
        io = _SocketIO(conn)
        pdb.Pdb.__init__(self, stdin=io, stdout=io)
        self.prompt = "(ray_tpu-pdb) "

    def cleanup(self):
        self._withdraw()
        for s in (self._conn, self._listener):
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass

    # pdb exits: always clean up the KV entry + sockets
    def do_continue(self, arg):
        try:
            return super().do_continue(arg)
        finally:
            self.cleanup()

    do_c = do_cont = do_continue

    def do_quit(self, arg):
        try:
            return super().do_quit(arg)
        finally:
            self.cleanup()

    do_q = do_exit = do_quit

    def do_EOF(self, arg):
        """Client detached (Ctrl-D): quit AND clean up — pdb's default EOF
        path skips do_quit, which would leak the KV entry + sockets."""
        try:
            return super().do_EOF(arg)
        finally:
            self.cleanup()


def set_trace(frame=None, label: Optional[str] = None):
    """Open a remote breakpoint and block for a client (reference:
    ray.util.rpdb.set_trace)."""
    rpdb = RemotePdb()
    rpdb._accept(label or "breakpoint")
    rpdb.set_trace(frame or sys._getframe().f_back)


def post_mortem(tb=None, label: Optional[str] = None):
    if tb is None:
        tb = sys.exc_info()[2]
    if tb is None:
        raise ValueError("no traceback to post-mortem")
    rpdb = RemotePdb()
    rpdb._accept(label or "post-mortem")
    try:
        rpdb.reset()
        rpdb.interaction(None, tb)
    finally:
        rpdb.cleanup()


def post_mortem_enabled() -> bool:
    return bool(os.environ.get("RAY_TPU_POST_MORTEM"))


def list_breakpoints(worker=None) -> List[dict]:
    """All currently-open remote breakpoints, from the GCS KV."""
    from ray_tpu._private.worker import get_global_worker

    w = worker or get_global_worker()
    keys = w.gcs.call("KVKeys", {"prefix": "debug:"}) or []
    out = []
    for k in keys:
        v = w.gcs.call("KVGet", {"key": k})
        if v:
            out.append({"key": k, **v})
    return out


def connect(host: str, port: int):
    """Interactive bridge: local terminal <-> remote pdb socket."""
    import select

    sock = socket.create_connection((host, int(port)))
    print(f"connected to {host}:{port}; Ctrl-D to detach", file=sys.stderr)
    try:
        while True:
            readable, _, _ = select.select([sock, sys.stdin], [], [])
            if sock in readable:
                data = sock.recv(4096)
                if not data:
                    break
                sys.stdout.write(data.decode("utf-8", "replace"))
                sys.stdout.flush()
            if sys.stdin in readable:
                line = sys.stdin.readline()
                if not line:
                    break
                sock.sendall(line.encode())
    finally:
        sock.close()
