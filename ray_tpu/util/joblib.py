"""joblib backend running on the distributed runtime.

reference: python/ray/util/joblib/ — `register_ray()` adds a joblib
parallel backend so scikit-learn-style `Parallel(n_jobs=...)` fan-outs run
as cluster tasks.  Implemented the same way the reference does: subclass
joblib's MultiprocessingBackend and hand it the framework's actor-backed
Pool (ray_tpu.util.multiprocessing) instead of OS processes.

    from ray_tpu.util.joblib import register_ray
    import joblib

    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        results = joblib.Parallel()(joblib.delayed(f)(x) for x in xs)
"""

from __future__ import annotations

from typing import Optional


def register_ray():
    """Register the 'ray_tpu' joblib backend (reference:
    util/joblib/__init__.py register_ray)."""
    import joblib

    joblib.register_parallel_backend("ray_tpu", RayTpuBackend)


try:
    from joblib._parallel_backends import MultiprocessingBackend
except ImportError:  # joblib absent: register_ray() will fail loudly instead
    MultiprocessingBackend = object  # type: ignore[misc,assignment]


class RayTpuBackend(MultiprocessingBackend):  # type: ignore[valid-type,misc]
    """reference: util/joblib/ray_backend.py RayBackend."""

    supports_sharedmem = False

    def configure(self, n_jobs: int = 1, parallel=None, prefer=None,
                  require=None, **memmapping_pool_kwargs):
        import ray_tpu
        from ray_tpu.util.multiprocessing import Pool

        n_jobs = self.effective_n_jobs(n_jobs)
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        # eager validation, then hand joblib a live pool
        self._pool = Pool(processes=n_jobs)
        self.parallel = parallel
        return n_jobs

    def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
        import ray_tpu

        if n_jobs == 0:
            raise ValueError("n_jobs == 0 in Parallel has no meaning")
        if n_jobs is None or n_jobs < 0:
            if ray_tpu.is_initialized():
                return max(int(ray_tpu.cluster_resources().get("CPU", 1)), 1)
            import os

            return os.cpu_count() or 1
        return n_jobs

    # terminate() is inherited: PoolManagerMixin closes + terminates the
    # pool, MultiprocessingBackend resets batch stats.
