"""Distributed FIFO queue backed by an actor.

reference: python/ray/util/queue.py.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: List[Any] = []

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def get(self):
        if not self.items:
            return (False, None)
        return (True, self.items.pop(0))

    def qsize(self) -> int:
        return len(self.items)

    def empty(self) -> bool:
        return not self.items


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        import ray_tpu

        cls = ray_tpu.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self.actor = cls.remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.put.remote(item)):
                return
            if not block or (deadline is not None and time.monotonic() > deadline):
                raise Full
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self.actor.get.remote())
            if ok:
                return item
            if not block or (deadline is not None and time.monotonic() > deadline):
                raise Empty
            time.sleep(0.01)

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self.actor.empty.remote())

    def shutdown(self):
        import ray_tpu

        ray_tpu.kill(self.actor)
