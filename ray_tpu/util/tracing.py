"""Distributed tracing over the task-event pipeline.

reference: python/ray/util/tracing/tracing_helper.py — OpenTelemetry spans
injected around task submit/execute, with the trace context serialized
into the TaskSpec so nested tasks, actor calls, and serve handlers chain
into ONE causal trace across processes.

Here the context is a per-thread ``(trace_id, span_id)`` pair:

  - ``span()`` opens a span under the active context (or roots a new
    trace) and records it as a pair of custom task events on the same
    sink tasks use (worker -> GcsServer task_events -> ray_tpu.timeline()
    / state.get_trace()), so user spans, runtime spans, and tasks all
    land on one Chrome trace with parent/child linkage.
  - ``CoreWorker.submit_task`` captures the context into the TaskSpec
    (``trace_id``/``parent_span_id``/``span_id``); the executor restores
    it around execution, so a task submitted inside a span — or inside
    another task — joins the submitter's trace.
  - serve's HTTP proxy ingests/emits the context as a W3C ``traceparent``
    header (``ingest()`` / ``format_traceparent()``).

Everything is gated by ``task_events_enabled and tracing_enabled``; the
disabled fast path is one config read plus one thread-local read.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, Optional, Tuple

_local = threading.local()

# process-local telemetry (bench.py trace_summary snapshot)
_spans_emitted = 0
_last_trace_id: Optional[str] = None


def _enabled() -> bool:
    from ray_tpu._private.config import global_config

    cfg = global_config()
    return cfg.task_events_enabled and cfg.tracing_enabled


def _worker():
    from ray_tpu._private.worker import get_global_worker

    try:
        return get_global_worker()
    except RuntimeError:
        return None


def new_trace_id() -> str:
    """32 lowercase hex chars (W3C trace-id width)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """16 lowercase hex chars (W3C parent-id width)."""
    return uuid.uuid4().hex[:16]


def current_context() -> Optional[Tuple[str, str]]:
    """The active ``(trace_id, span_id)``, or None outside any span/task."""
    return getattr(_local, "ctx", None)


def context_active() -> bool:
    """Cheap hot-path guard: is there an active trace on this thread?"""
    return getattr(_local, "ctx", None) is not None


@contextlib.contextmanager
def activate(trace_id: str, span_id: Optional[str]) -> Iterator[None]:
    """Make ``(trace_id, span_id)`` the active context on this thread.

    Used to carry a context across thread hops (executor pools, the data
    streaming-executor scheduling thread) — it records nothing itself.
    """
    prev = getattr(_local, "ctx", None)
    _local.ctx = (trace_id, span_id)
    try:
        yield
    finally:
        _local.ctx = prev


def activate_from_spec(spec):
    """Executor side: restore the submitter's context around execution so
    spans and nested submissions inside the task chain into its trace.
    The task's own span_id becomes the parent of everything inside."""
    trace_id = getattr(spec, "trace_id", None)
    if trace_id is None:
        return contextlib.nullcontext()
    return activate(trace_id, getattr(spec, "span_id", None))


def capture_for_submit() -> Tuple[Optional[str], Optional[str], Optional[str]]:
    """Owner side: ``(trace_id, parent_span_id, span_id)`` for a TaskSpec.

    Only submissions inside an active span/task join a trace — tracing is
    EXPLICIT (a ``span()``, a ``traceparent`` ingress, or an enclosing
    traced task).  Untraced submissions stay id-free: auto-rooting every
    task would activate a context in every executor and flood the bounded
    task sink with per-collective/engine/data spans nobody asked for.
    """
    ctx = getattr(_local, "ctx", None)
    if ctx is not None and _enabled():
        return ctx[0], ctx[1], new_span_id()
    return None, None, None


# -- W3C traceparent (https://www.w3.org/TR/trace-context/) ----------------


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` or None for a malformed header."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def ingest(traceparent: Optional[str] = None
           ) -> Optional[Tuple[str, str, Optional[str]]]:
    """Ingress helper: ``(trace_id, span_id, parent_span_id)`` for a new
    server-side request span, continuing the caller's trace when a valid
    ``traceparent`` header is supplied.  None when tracing is disabled."""
    if not _enabled() or _worker() is None:
        return None
    parsed = parse_traceparent(traceparent)
    if parsed is not None:
        return parsed[0], new_span_id(), parsed[1]
    return new_trace_id(), new_span_id(), None


# -- span recording --------------------------------------------------------


def emit_span(name: str, start: float, end: float, *,
              kind: str = "span",
              attributes: Optional[Dict[str, Any]] = None,
              trace_id: Optional[str] = None,
              parent_span_id: Optional[str] = None,
              span_id: Optional[str] = None,
              flush: bool = False) -> Optional[str]:
    """Record an already-completed span (wall-clock ``start``/``end``).

    The cheap recorder used by built-in hot paths (collectives, engine
    step phases, data operators): when no explicit ``trace_id`` is given
    it no-ops unless a context is active, so the disabled/untraced cost
    is two attribute reads.  Returns the span_id, or None if dropped.
    """
    if not _enabled():
        return None
    if trace_id is None:
        ctx = getattr(_local, "ctx", None)
        if ctx is None:
            return None
        trace_id = ctx[0]
        if parent_span_id is None:
            parent_span_id = ctx[1]
    w = _worker()
    if w is None:
        return None
    sid = span_id or new_span_id()
    actor_id = getattr(w, "actor_id", None)
    base = {
        "task_id": f"span-{sid}",
        "name": name,
        "attempt": 0,
        "kind": kind,
        "job_id": w.job_id.hex() if w.job_id else None,
        "actor_id": actor_id.hex() if actor_id else None,
        "pid": os.getpid(),
        "node_id": w.node_id.hex() if w.node_id else None,
        "trace_id": trace_id,
        "span_id": sid,
        "parent_span_id": parent_span_id,
    }
    # staleness bound without per-span GCS messages: the >=100 batch
    # threshold, task-completion flushes, and the worker's periodic loop
    # (resubscribe tick) flushing buffered events for processes that
    # never execute tasks (HTTP proxy hosts, idle drivers)
    w.append_task_events(
        [{**base, "state": "RUNNING", "time": start,
          **({"attributes": attributes} if attributes else {})},
         {**base, "state": "FINISHED", "time": end}],
        flush=flush)
    global _spans_emitted, _last_trace_id
    _spans_emitted += 1
    _last_trace_id = trace_id
    return sid


class Span:
    """Handle yielded by ``span()``: the ids needed to propagate the
    context out of band (e.g. a ``traceparent`` response header)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id, span_id, parent_span_id):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None,
         kind: str = "span") -> Iterator[Optional[Span]]:
    """Open a named span on this thread.

    with tracing.span("preprocess-batch"):
        ...  # nested spans / task submissions chain under it

    Joins the active trace (the enclosing span or executing task) or
    roots a new one.  Yields a ``Span`` handle (None when disabled).
    """
    if not (_enabled() and _worker() is not None):
        yield None
        return
    ctx = getattr(_local, "ctx", None)
    trace_id = ctx[0] if ctx else new_trace_id()
    parent = ctx[1] if ctx else None
    sid = new_span_id()
    start = time.time()
    try:
        with activate(trace_id, sid):
            yield Span(trace_id, sid, parent)
    finally:
        # batched (>=100-event threshold) like every hot-path span: task
        # completion flushes worker-side buffers, and timeline()/get_trace()
        # flush the local one — a per-span GCS notify would scale ingest
        # messages with request rate
        emit_span(name, start, time.time(), kind=kind, attributes=attributes,
                  trace_id=trace_id, parent_span_id=parent, span_id=sid)


@contextlib.contextmanager
def activate_span(ctx3: Optional[Tuple[str, str, Optional[str]]], name: str,
                  attributes: Optional[Dict[str, Any]] = None,
                  kind: str = "server") -> Iterator[None]:
    """Run the body under a pre-created ingress context from ``ingest()``
    (the ids must exist before the body runs so response headers can be
    written first). No-op when ``ctx3`` is None."""
    if ctx3 is None:
        yield
        return
    trace_id, sid, parent = ctx3
    start = time.time()
    try:
        with activate(trace_id, sid):
            yield
    finally:
        emit_span(name, start, time.time(), kind=kind, attributes=attributes,
                  trace_id=trace_id, parent_span_id=parent, span_id=sid)


class PhaseRecorder:
    """Stamp-under-lock / emit-after-release span recording for engine-style
    hot loops: ``emit_span`` may flush to the GCS (socket I/O), which must
    never run while holding a serving lock.  Stamp phases while locked,
    call ``emit()`` once outside.

        rec = tracing.PhaseRecorder()
        with self._lock:
            if rec.active:
                t0 = time.time()
            ...work...
            if rec.active:
                rec.stamp("engine.decode", t0, {"chunk": n})
        rec.emit()
    """

    __slots__ = ("active", "_spans")

    def __init__(self):
        self.active = context_active()
        self._spans = []

    def stamp(self, name: str, start: float,
              attributes: Optional[Dict[str, Any]] = None):
        self._spans.append((name, start, time.time(), attributes))

    def emit(self, kind: str = "engine"):
        for name, t0, t1, attrs in self._spans:
            emit_span(name, t0, t1, kind=kind, attributes=attrs)
        self._spans.clear()


def trace_function(fn=None, *, name: Optional[str] = None):
    """Decorator form (reference: tracing_helper's decorator rewriting)."""
    import functools

    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with span(name or f.__qualname__):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco


def trace_summary_snapshot() -> dict:
    """Process-local tracing telemetry for bench.py's JSON line; includes
    a critical-path summary of the last trace when a cluster is up."""
    out = {
        "enabled": _enabled(),
        "spans_emitted": _spans_emitted,
        "last_trace_id": _last_trace_id,
    }
    if _last_trace_id and _worker() is not None:
        try:
            from ray_tpu.util.state import summarize_trace

            out["last_trace_summary"] = summarize_trace(_last_trace_id)
        except Exception as e:  # noqa: BLE001 — snapshot must never fail
            out["last_trace_summary"] = {"error": str(e)[:200]}
    return out
