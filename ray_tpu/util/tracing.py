"""Tracing spans over the task-event pipeline.

reference: python/ray/util/tracing/tracing_helper.py — OpenTelemetry spans
injected around task submit/execute.  Here spans reuse the framework's
task-event sink (worker -> GcsServer task_events -> ray_tpu.timeline()):
a span is recorded as a pair of custom task events, so user spans appear
on the same Chrome trace as tasks, with zero extra infrastructure.
"""

from __future__ import annotations

import contextlib
import os
import time
import uuid
from typing import Any, Dict, Iterator, Optional


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None) -> Iterator[None]:
    """Record a named span on the cluster timeline.

    with tracing.span("preprocess-batch"):
        ...
    """
    from ray_tpu._private.config import global_config
    from ray_tpu._private.worker import get_global_worker

    try:
        w = get_global_worker()
    except RuntimeError:
        w = None
    enabled = w is not None and global_config().task_events_enabled
    span_id = uuid.uuid4().hex[:16]
    start = time.time()
    try:
        yield
    finally:
        if enabled:
            actor_id = getattr(w, "actor_id", None)
            base = {
                "task_id": f"span-{span_id}",
                "name": name,
                "attempt": 0,
                "job_id": w.job_id.hex() if w.job_id else None,
                "actor_id": actor_id.hex() if actor_id else None,
                "pid": os.getpid(),
                "node_id": w.node_id.hex() if w.node_id else None,
            }
            w._task_events.append({**base, "state": "RUNNING", "time": start,
                                   **({"attributes": attributes} if attributes else {})})
            w._task_events.append({**base, "state": "FINISHED", "time": time.time()})
            w.flush_task_events()


def trace_function(fn=None, *, name: Optional[str] = None):
    """Decorator form (reference: tracing_helper's decorator rewriting)."""
    import functools

    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            with span(name or f.__qualname__):
                return f(*args, **kwargs)

        return wrapper

    return deco(fn) if fn is not None else deco
