"""Server half of the Ray-Client-equivalent proxy.

reference: python/ray/util/client/server/ — the in-cluster server that holds
real ObjectRefs/actor handles on behalf of remote clients and proxies API
calls.  One shared in-cluster driver serves all sessions; each session's refs
are pinned server-side until the client releases them (or the session is
reaped after ``idle_timeout_s`` without traffic).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private.utils import DaemonExecutor

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcServer
from ray_tpu._private.worker import ObjectRef


class _Session:
    def __init__(self, session_id: str):
        self.id = session_id
        self.refs: Dict[str, ObjectRef] = {}  # object_id hex -> pinned ref
        self.actors: list = []  # (actor_id, detached)
        self.last_seen = time.monotonic()
        self.lock = threading.Lock()
        # op-token -> reply, so a client resend after a connection blip
        # returns the original result instead of re-running the mutation
        self.op_cache: "OrderedDict[str, Any]" = OrderedDict()

    def touch(self):
        self.last_seen = time.monotonic()

    def cached_op(self, token: Optional[str]):
        if token is None:
            return None
        with self.lock:
            return self.op_cache.get(token)

    def cache_op(self, token: Optional[str], reply):
        if token is None:
            return
        with self.lock:
            self.op_cache[token] = reply
            while len(self.op_cache) > 4096:
                self.op_cache.popitem(last=False)

    def pin(self, ref_or_refs):
        refs = ref_or_refs if isinstance(ref_or_refs, list) else [ref_or_refs]
        with self.lock:
            for r in refs:
                self.refs[r.id.hex()] = r
        if isinstance(ref_or_refs, list):
            return [(r.id, r.owner_addr) for r in ref_or_refs]
        return (ref_or_refs.id, ref_or_refs.owner_addr)


class ClientServer:
    """Hosts remote client sessions over the framework RPC transport."""

    def __init__(self, port: int = 10001, host: str = "127.0.0.1",
                 address=None, idle_timeout_s: float = 300.0,
                 auth_token: Optional[str] = None, **init_kwargs):
        """``host`` defaults to loopback; to serve external clients bind an
        explicit interface AND set ``auth_token`` (also via the
        RAY_TPU_CLIENT_TOKEN env var) — the transport is pickle-based, so an
        open unauthenticated port is remote code execution for anyone who
        can reach it."""
        import os

        import ray_tpu

        if auth_token is None:
            auth_token = os.environ.get("RAY_TPU_CLIENT_TOKEN")
        if host not in ("127.0.0.1", "localhost", "::1") and not auth_token:
            raise ValueError(
                f"refusing to bind ClientServer on {host!r} without an "
                "auth_token (set one, or RAY_TPU_CLIENT_TOKEN)")
        self._auth_token = auth_token
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, **init_kwargs)
        self._worker = ray_tpu.get_global_worker()
        self._sessions: Dict[str, _Session] = {}
        self._connect_cache: "OrderedDict[str, str]" = OrderedDict()
        self._lock = threading.Lock()
        self._idle_timeout_s = idle_timeout_s
        self._stopped = threading.Event()
        # Off-loopback the token doubles as a transport-level handshake,
        # checked byte-for-byte BEFORE any frame is unpickled (the payloads
        # are pickles; unauthenticated unpickling would be code execution).
        self._server = RpcServer(host=host, port=port,
                                 handshake_token=auth_token)
        self._server.register_all(self, prefix="Client")
        # Blocking get/wait calls run here so they can't starve the RPC
        # handler pool (pings/releases must keep flowing while gets block).
        self._blocking_pool = DaemonExecutor(max_workers=64,
                                             thread_name_prefix="client-blocking")
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name="client-server-reaper")
        self._reaper.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.address

    def wait(self):
        self._stopped.wait()

    def shutdown(self):
        self._stopped.set()
        for sid in list(self._sessions):
            self._drop_session(sid)
        self._server.shutdown()
        self._blocking_pool.shutdown(wait=False, cancel_futures=True)

    # ------------------------------------------------------------------

    def _session(self, payload) -> _Session:
        s = self._sessions.get(payload["session"])
        if s is None:
            raise RuntimeError(f"unknown client session {payload.get('session')!r} "
                               "(reaped after idle timeout? reconnect)")
        s.touch()
        return s

    def _reap_loop(self):
        while not self._stopped.wait(10.0):
            now = time.monotonic()
            for sid, s in list(self._sessions.items()):
                if now - s.last_seen > self._idle_timeout_s:
                    self._drop_session(sid)

    def _drop_session(self, session_id: str):
        with self._lock:
            s = self._sessions.pop(session_id, None)
        if s is None:
            return
        with s.lock:
            s.refs.clear()
        # Non-detached actors created by the session die with it, matching
        # driver-exit semantics (reference: owned actors die with the owner).
        for actor_id, detached in s.actors:
            if not detached:
                try:
                    self._worker.kill_actor(actor_id, no_restart=True)
                except Exception:  # noqa: BLE001 — actor already died with its session
                    pass

    def _resolve_ref(self, s: _Session, packed) -> ObjectRef:
        object_id, owner_addr = packed
        ref = s.refs.get(object_id.hex())
        return ref if ref is not None else ObjectRef(object_id, owner_addr)

    def _unpack_args(self, s: _Session, blob: bytes):
        args, kwargs = serialization.loads_inline(blob)
        return args, kwargs

    # ------------------------------------------------------------------
    # Handlers (registered as Client<Name>)
    # ------------------------------------------------------------------

    def HandleConnect(self, payload):
        if self._auth_token and payload.get("auth") != self._auth_token:
            raise PermissionError("client auth token missing or wrong")
        token = payload.get("op")
        with self._lock:
            if token is not None and token in self._connect_cache:
                session_id = self._connect_cache[token]
            else:
                session_id = uuid.uuid4().hex
                self._sessions[session_id] = _Session(session_id)
                if token is not None:
                    self._connect_cache[token] = session_id
                    while len(self._connect_cache) > 4096:
                        self._connect_cache.popitem(last=False)
        return {"session": session_id, "server_pid": __import__("os").getpid(),
                "job_id": getattr(self._worker, "job_id", None)}

    def HandleDisconnect(self, payload):
        self._drop_session(payload["session"])
        return True

    def HandlePing(self, payload):
        self._session(payload)
        return True

    def HandlePut(self, payload):
        s = self._session(payload)
        cached = s.cached_op(payload.get("op"))
        if cached is not None:
            return cached
        value = serialization.loads_inline(payload["blob"])
        reply = s.pin(self._worker.put(value))
        s.cache_op(payload.get("op"), reply)
        return reply

    def _session_alive(self, s: _Session) -> bool:
        return not self._stopped.is_set() and s.id in self._sessions

    def _poll_until(self, s: _Session, refs, num_returns, timeout):
        """Wait in short slices so an abandoned call frees its pool thread
        when the session dies, instead of wedging the blocking pool forever."""
        uniq = list({r.id: r for r in refs}.values())
        num_returns = min(num_returns, len(uniq))
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if not self._session_alive(s):
                raise ConnectionError("client session closed while waiting")
            slice_t = 2.0
            if deadline is not None:
                slice_t = min(2.0, max(0.0, deadline - time.monotonic()))
            ready, not_ready = self._worker.wait(
                uniq, num_returns=num_returns, timeout=slice_t)
            if len(ready) >= num_returns:
                return ready, not_ready
            if deadline is not None and time.monotonic() >= deadline:
                return ready, not_ready

    def HandleGet(self, payload, reply_token):
        s = self._session(payload)
        refs = [self._resolve_ref(s, p) for p in payload["refs"]]

        def run():
            try:
                timeout = payload.get("timeout")
                start = time.monotonic()
                ready, _ = self._poll_until(
                    s, refs, len({r.id for r in refs}), timeout)
                if len(ready) < len({r.id for r in refs}):
                    from ray_tpu._private.task_spec import GetTimeoutError

                    raise GetTimeoutError(f"get() timed out after {timeout}s")
                # readiness consumed part of the budget; the data fetch gets
                # the remainder (or forever, matching timeout=None semantics)
                remaining = (None if timeout is None
                             else max(0.1, timeout - (time.monotonic() - start)))
                values = self._worker.get(refs, timeout=remaining)
                if not isinstance(values, list):
                    values = [values]
                self._server.send_reply(
                    reply_token, [serialization.dumps_inline(v) for v in values])
            except Exception as e:  # noqa: BLE001
                self._server.send_error_reply(reply_token, e)

        self._blocking_pool.submit(run)
        return RpcServer.DELAYED_REPLY

    def HandleWait(self, payload, reply_token):
        s = self._session(payload)
        refs = [self._resolve_ref(s, p) for p in payload["refs"]]

        def run():
            try:
                ready, not_ready = self._poll_until(
                    s, refs, payload["num_returns"], payload.get("timeout"))
                self._server.send_reply(
                    reply_token,
                    ([r.id.hex() for r in ready], [r.id.hex() for r in not_ready]))
            except Exception as e:  # noqa: BLE001
                self._server.send_error_reply(reply_token, e)

        self._blocking_pool.submit(run)
        return RpcServer.DELAYED_REPLY

    def HandleSubmitTask(self, payload):
        s = self._session(payload)
        cached = s.cached_op(payload.get("op"))
        if cached is not None:
            return cached
        fn = serialization.loads_inline(payload["fn"])
        args, kwargs = self._unpack_args(s, payload["args"])
        refs = self._worker.submit_task(fn, args, kwargs, **payload["options"])
        reply = s.pin(refs)
        s.cache_op(payload.get("op"), reply)
        return reply

    def HandleCreateActor(self, payload):
        s = self._session(payload)
        cached = s.cached_op(payload.get("op"))
        if cached is not None:
            return cached
        cls = serialization.loads_inline(payload["cls"])
        args, kwargs = self._unpack_args(s, payload["args"])
        options = payload["options"]
        actor_id, _spec = self._worker.create_actor(cls, args, kwargs, **options)
        s.actors.append((actor_id, options.get("lifetime") == "detached"))
        s.cache_op(payload.get("op"), actor_id)
        return actor_id

    def HandleSubmitActorTask(self, payload):
        s = self._session(payload)
        cached = s.cached_op(payload.get("op"))
        if cached is not None:
            return cached
        args, kwargs = self._unpack_args(s, payload["args"])
        refs = self._worker.submit_actor_task(
            payload["actor_id"], payload["method"], args, kwargs,
            num_returns=payload["num_returns"],
            max_task_retries=payload.get("max_task_retries", 0),
            concurrency_group=payload.get("concurrency_group"))
        reply = s.pin(refs)
        s.cache_op(payload.get("op"), reply)
        return reply

    def HandleKillActor(self, payload):
        self._session(payload)
        return self._worker.kill_actor(payload["actor_id"],
                                       no_restart=payload.get("no_restart", True))

    def HandleGetNamedActor(self, payload):
        self._session(payload)
        return self._worker.get_named_actor(payload["name"],
                                            payload.get("namespace", "default"))

    def HandleRefDeserialized(self, payload):
        """A ref nested inside a value was unpickled client-side; play the
        borrowing worker's half of the transit protocol here and pin the ref
        for the session (released via the normal ClientRelease path)."""
        s = self._session(payload)
        object_id, owner_addr = payload["ref"]
        ref = ObjectRef(object_id, owner_addr)
        self._worker.reference_counter.on_ref_deserialized(ref)
        s.pin(ref)
        return True

    def HandleRefSerialized(self, payload):
        """A session ref was pickled into client-side args; pre-balance the
        transit count the server-side unpickle will consume."""
        s = self._session(payload)
        object_id, owner_addr = payload["ref"]
        ref = s.refs.get(object_id.hex()) or ObjectRef(object_id, owner_addr)
        self._worker.reference_counter.on_ref_serialized(ref)
        return True

    def HandleRelease(self, payload):
        s = self._session(payload)
        with s.lock:
            for object_id in payload["ids"]:
                s.refs.pop(object_id, None)
        return True

    def HandleFlushTaskEvents(self, payload):
        self._session(payload)
        self._worker.flush_task_events()
        return True

    def HandleGcsCall(self, payload):
        """Forward control-plane reads/writes (nodes, state API, KV)."""
        self._session(payload)
        return self._worker.gcs.call(payload["method"], payload["payload"])
