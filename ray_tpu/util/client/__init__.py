"""Ray-Client-equivalent proxy mode (``ray://host:port``).

reference: python/ray/util/client/ + ray_client.proto — a gRPC proxy server
runs inside the cluster and external processes drive the full task/actor/
object API through it.  Here the proxy rides the framework's own RPC layer
(ray_tpu/_private/rpc.py) instead of gRPC.

Usage, server side (a process on the cluster)::

    from ray_tpu.util.client.server import ClientServer
    srv = ClientServer(port=10001)        # init()s a local cluster if needed
    srv.wait()                            # serve forever

Client side (any machine that can reach the port)::

    ray_tpu.init("ray://127.0.0.1:10001")
    # full API: @remote fns, actors, get/put/wait, named actors, state.
"""

from ray_tpu.util.client.worker import ClientWorker, connect

__all__ = ["ClientWorker", "connect"]
