"""Client half of the Ray-Client-equivalent proxy.

reference: python/ray/util/client/worker.py — implements the same narrow
worker surface the API layer (remote_function.py / actor.py / __init__.py)
drives, but every call is forwarded to an in-cluster ClientServer which holds
the real refs.  ``ray_tpu.init("ray://host:port")`` constructs one of these
and installs it as the global worker, so the full public API works unchanged
from outside the cluster.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private.ids import ActorID, WorkerID
from ray_tpu._private.rpc import RpcClient
from ray_tpu._private.worker import ObjectRef


class _ClientReferenceCounter:
    """Counts client-local refs; releases server pins when they hit zero."""

    def __init__(self, worker: "ClientWorker"):
        self._worker = worker
        self._counts: Dict[bytes, int] = {}
        self._lock = threading.Lock()

    def add_local_ref(self, ref: ObjectRef):
        key = ref.id.hex()
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1

    def remove_local_ref(self, ref: ObjectRef):
        key = ref.id.hex()
        release = False
        with self._lock:
            n = self._counts.get(key, 0) - 1
            if n <= 0:
                self._counts.pop(key, None)
                release = True
            else:
                self._counts[key] = n
        if release:
            self._worker._release([key])

    # The owner's serialize(+transit)/deserialize(-transit, +borrower)
    # pairing must stay balanced when one side of the pair happens in the
    # client process, so both events are forwarded to the server, which acts
    # as the borrowing worker on this session's behalf.  Same-socket FIFO
    # guarantees the notification lands before the op that carries the ref.
    def on_ref_serialized(self, ref: ObjectRef):
        self._worker._notify("ClientRefSerialized",
                             {"ref": (ref.id, ref.owner_addr)})

    def on_ref_deserialized(self, ref: ObjectRef):
        self._worker._notify("ClientRefDeserialized",
                             {"ref": (ref.id, ref.owner_addr)})


class _GcsProxy:
    def __init__(self, worker: "ClientWorker"):
        self._worker = worker

    def call(self, method: str, payload=None, **_kw):
        return self._worker._call("ClientGcsCall",
                                  {"method": method, "payload": payload})


class ClientWorker:
    """Global-worker stand-in speaking to a remote ClientServer."""

    def __init__(self, address: Tuple[str, int]):
        import os

        token = os.environ.get("RAY_TPU_CLIENT_TOKEN")
        self._rpc = RpcClient(tuple(address), handshake_token=token)
        self.shutting_down = False
        # op token so a resend after a connection blip reuses the session
        # instead of leaking an orphan server-side
        reply = self._rpc.call("ClientConnect", {
            "op": uuid.uuid4().hex,
            "auth": token,
        })
        self._session = reply["session"]
        # RuntimeContext surface (reference: runtime_context.py reads these
        # off the global worker); tasks/actors never run in a client process.
        self.job_id = reply.get("job_id")
        self.node_id = None
        self.worker_id = WorkerID.random()
        self.actor_id = None
        self.current_task_id = None
        self.reference_counter = _ClientReferenceCounter(self)
        self.gcs = _GcsProxy(self)
        self._heartbeat_stop = threading.Event()
        self._heartbeat = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True, name="client-heartbeat")
        self._heartbeat.start()

    # ------------------------------------------------------------------

    def _call(self, method: str, payload: dict, timeout=RpcClient._DEFAULT_TIMEOUT):
        payload["session"] = self._session
        return self._rpc.call(method, payload, timeout=timeout)

    def _notify(self, method: str, payload: dict):
        if self.shutting_down:
            return
        payload["session"] = self._session
        try:
            self._rpc.notify(method, payload)
        except Exception:  # noqa: BLE001 — fire-and-forget notify; server may be gone
            pass

    def _release(self, ids: List[bytes]):
        self._notify("ClientRelease", {"ids": ids})

    def _heartbeat_loop(self):
        while not self._heartbeat_stop.wait(30.0):
            try:
                self._call("ClientPing", {})
            except Exception:  # noqa: BLE001 — ping fails while the server restarts; loop retries
                pass

    def _make_ref(self, packed) -> ObjectRef:
        object_id, owner_addr = packed
        return ObjectRef(object_id, owner_addr)

    def _pack_refs(self, refs) -> list:
        return [(r.id, r.owner_addr) for r in refs]

    # ------------------------------------------------------------------
    # CoreWorker surface used by the API layer
    # ------------------------------------------------------------------

    def put(self, value) -> ObjectRef:
        packed = self._call("ClientPut",
                            {"blob": serialization.dumps_inline(value),
                             "op": uuid.uuid4().hex},
                            timeout=None)
        return self._make_ref(packed)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        blobs = self._call(
            "ClientGet",
            {"refs": self._pack_refs(ref_list), "timeout": timeout},
            timeout=None)
        values = [serialization.loads_inline(b) for b in blobs]
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ready_ids, _ = self._call(
            "ClientWait",
            {"refs": self._pack_refs(refs), "num_returns": num_returns,
             "timeout": timeout, "fetch_local": fetch_local},
            timeout=None)
        ready_set = set(ready_ids)
        ready = [r for r in refs if r.id.hex() in ready_set]
        not_ready = [r for r in refs if r.id.hex() not in ready_set]
        return ready, not_ready

    def submit_task(self, fn, args, kwargs, *, name=None, num_returns=1,
                    resources=None, strategy=None, max_retries=None,
                    retry_exceptions=False, runtime_env=None):
        packed = self._call("ClientSubmitTask", {
            "fn": serialization.dumps_inline(fn),
            "args": serialization.dumps_inline((tuple(args), dict(kwargs or {}))),
            "options": dict(name=name, num_returns=num_returns, resources=resources,
                            strategy=strategy, max_retries=max_retries,
                            retry_exceptions=retry_exceptions, runtime_env=runtime_env),
            "op": uuid.uuid4().hex,
        }, timeout=None)
        if num_returns == 1:
            return self._make_ref(packed)
        return [self._make_ref(p) for p in packed]

    def create_actor(self, cls, args, kwargs, *, name=None, num_returns=1,
                     resources=None, strategy=None, max_restarts=0,
                     max_task_retries=0, max_concurrency=1, concurrency_groups=None,
                     lifetime=None, namespace="default", runtime_env=None):
        actor_id = self._call("ClientCreateActor", {
            "cls": serialization.dumps_inline(cls),
            "args": serialization.dumps_inline((tuple(args), dict(kwargs or {}))),
            "options": dict(name=name, resources=resources, strategy=strategy,
                            max_restarts=max_restarts, max_task_retries=max_task_retries,
                            max_concurrency=max_concurrency,
                            concurrency_groups=concurrency_groups, lifetime=lifetime,
                            namespace=namespace, runtime_env=runtime_env),
            "op": uuid.uuid4().hex,
        }, timeout=None)
        return actor_id, None

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args, kwargs,
                          num_returns=1, max_task_retries=0, concurrency_group=None):
        packed = self._call("ClientSubmitActorTask", {
            "actor_id": actor_id,
            "method": method_name,
            "args": serialization.dumps_inline((tuple(args), dict(kwargs or {}))),
            "num_returns": num_returns,
            "max_task_retries": max_task_retries,
            "concurrency_group": concurrency_group,
            "op": uuid.uuid4().hex,
        }, timeout=None)
        if num_returns == 1:
            return self._make_ref(packed)
        return [self._make_ref(p) for p in packed]

    def kill_actor(self, actor_id: ActorID, no_restart=True):
        return self._call("ClientKillActor",
                          {"actor_id": actor_id, "no_restart": no_restart})

    def get_named_actor(self, name: str, namespace="default"):
        return self._call("ClientGetNamedActor",
                          {"name": name, "namespace": namespace})

    def flush_task_events(self):
        """ray_tpu.timeline() support: flush the in-cluster driver's buffer."""
        return self._call("ClientFlushTaskEvents", {})

    def shutdown(self):
        self.shutting_down = True
        self._heartbeat_stop.set()
        try:
            self._rpc.call("ClientDisconnect", {"session": self._session}, timeout=5)
        except Exception:  # noqa: BLE001 — server gone: the disconnect is implicit
            pass
        self._rpc.close()


def connect(address) -> ClientWorker:
    """Parse ``ray://host:port`` (or (host, port)) and open a client session."""
    if isinstance(address, str):
        from ray_tpu._private.utils import parse_host_port

        address = address[len("ray://"):] if address.startswith("ray://") else address
        address = parse_host_port(address)
    return ClientWorker(tuple(address))
