"""Named store actor used for collective rendezvous + the STORE data plane.

reference: python/ray/util/collective/collective_group/nccl_collective_group.py:30-82
(Rendezvous via a named store actor holding the NCCLUniqueID; store name from
const.py get_store_name). Here the same pattern serves (a) publishing the
jax.distributed coordinator address for the XLA backend, and (b) the full
data plane for the STORE backend.

Prompt abort (preemption-aware fault tolerance): group members register
their identity (actor id + node id) on join; a background monitor inside
the store actor polls the GCS and, when a member dies or its node starts
DRAINING, poisons the group — every blocked ``store_wait`` (and every write)
sees the abort sentinel within seconds and raises ``CollectiveAbortError``
instead of hanging to the stock timeout.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.util.collective.types import CollectiveAbortError

logger = logging.getLogger(__name__)

STORE_ACTOR_NAME = "_ray_tpu_collective_store"

# sentinel value store methods return for a poisoned group — store_wait and
# the write-side helpers turn it into a CollectiveAbortError at the caller
ABORT_SENTINEL = "__ray_tpu_collective_abort__"


def is_abort(value) -> bool:
    return (isinstance(value, tuple) and len(value) == 2
            and value[0] == ABORT_SENTINEL)


class _CollectiveStoreActor:
    """KV + barrier + gather primitives, garbage-collected by read counts."""

    def __init__(self):
        self._kv: Dict[Any, Any] = {}
        self._gathers: Dict[Tuple, Dict[int, Any]] = {}
        self._gather_reads: Dict[Tuple, set] = {}
        self._barriers: Dict[Tuple, set] = {}
        self._barrier_reads: Dict[Tuple, set] = {}
        self._groups: Dict[str, dict] = {}
        # group_name -> abort reason (poisoned until re-declared)
        self._aborts: Dict[str, str] = {}
        # group_name -> rank -> {"actor_id": hex|None, "node_id": hex|None}
        self._members: Dict[str, Dict[int, dict]] = {}
        self._monitor_started = False
        # -- per-member arrival monitor (hang & straggler diagnosis) -------
        # every collective round's key records who arrived when; a round
        # stuck with missing ranks is the hang signature, and completed
        # rounds feed a per-(group, rank) arrival-lag EWMA (persistent
        # stragglers score high).  Injectable clock for hermetic tests.
        self._clock = time.monotonic
        # key -> {"first": t, "by_rank": {rank: t}, "expected": int|None}
        self._arrivals: Dict[Tuple, dict] = {}
        self._lag_ewma: Dict[Tuple[str, int], float] = {}

    # -- arrival monitor ----------------------------------------------------
    def _stamp_arrival(self, key: Tuple, rank: int,
                       expected: Optional[int] = None,
                       expected_ranks=None):
        """``rank`` is always the member's GROUP-GLOBAL rank (subgroup
        rounds translate their subranks before stamping — lag EWMAs and
        blocking-member resolution are keyed by global rank).
        ``expected_ranks`` names the global ranks a subgroup round waits
        for; plain rounds use ``expected`` (a count over range(world))."""
        if not (isinstance(key, tuple) and len(key) >= 2):
            return
        now = self._clock()
        ent = self._arrivals.get(key)
        if ent is None:
            ent = self._arrivals[key] = {
                "first": now, "by_rank": {}, "expected": expected,
                "ranks": None}
        ent["by_rank"].setdefault(rank, now)
        if expected_ranks is not None:
            ent["ranks"] = list(expected_ranks)
            ent["expected"] = len(ent["ranks"])
        elif expected is not None:
            ent["expected"] = expected
        exp = ent["expected"]
        if exp is not None and len(ent["by_rank"]) >= exp:
            self._complete_round(key, ent)

    def _note_expected(self, key: Tuple, expected: int):
        """collect() polls carry the round's world size — a round whose
        contribute side never learned it (gather rounds) gets it from the
        first waiting reader, so missing ranks become computable."""
        ent = self._arrivals.get(key)
        if ent is not None and ent.get("expected") is None:
            ent["expected"] = expected
            if len(ent["by_rank"]) >= expected:
                self._complete_round(key, ent)

    def _complete_round(self, key: Tuple, ent: dict):
        """All members arrived: fold per-member lag (vs the round's first
        arrival) into the persistent straggler EWMA and drop the entry."""
        self._arrivals.pop(key, None)
        group = key[0]
        first = ent["first"]
        try:
            from ray_tpu._private.config import global_config

            alpha = global_config().straggler_ewma_alpha
        except Exception:  # noqa: BLE001
            alpha = 0.2
        for rank, t in ent["by_rank"].items():
            lag = max(t - first, 0.0)
            k = (group, rank)
            prev = self._lag_ewma.get(k)
            ewma = lag if prev is None else (alpha * lag + (1 - alpha) * prev)
            self._lag_ewma[k] = ewma
            try:
                from ray_tpu._private import runtime_metrics

                runtime_metrics.set_straggler_lag(group, rank, ewma)
            except Exception:  # noqa: BLE001 — lag gauge is telemetry; the monitor stays correct
                pass

    def straggler_report(self, group_name: Optional[str] = None) -> dict:
        """Live arrival view for ``state.diagnose()``: per group, the
        pending rounds (kind+seq, who arrived, who is missing, how long the
        round has waited) and the persistent per-rank arrival-lag EWMA.
        Missing ranks are resolved against the round's expected count when
        known, else the group's declared world size."""
        now = self._clock()
        groups: Dict[str, dict] = {}

        def _group_entry(g: str) -> dict:
            return groups.setdefault(g, {
                "pending": [],
                "lag_ewma_s": {},
                "members": dict(self._members.get(g, {})),
                "world_size": (self._groups.get(g) or {}).get("world_size"),
                "aborted": self._aborts.get(g),
            })

        for key, ent in list(self._arrivals.items()):
            g = key[0]
            if group_name is not None and g != group_name:
                continue
            d = _group_entry(g)
            expected = ent.get("expected") or d["world_size"]
            arrived = sorted(ent["by_rank"])
            ranks = ent.get("ranks")
            if ranks:  # subgroup round: members are named, not range()
                missing = sorted(set(ranks) - set(arrived))
            else:
                missing = (sorted(set(range(expected)) - set(arrived))
                           if expected else [])
            d["pending"].append({
                "op": key[1] if len(key) > 1 else "?",
                "seq": key[2] if len(key) > 2 else None,
                "waiting_s": round(now - ent["first"], 3),
                "arrived": arrived,
                "missing": missing,
                "expected": expected,
            })
        for (g, rank), ewma in self._lag_ewma.items():
            if group_name is not None and g != group_name:
                continue
            _group_entry(g)["lag_ewma_s"][rank] = round(ewma, 4)
        # groups with members but no activity still appear (identity map
        # is what diagnose uses to name a missing member's actor/node)
        for g in list(self._members):
            if group_name is None or g == group_name:
                _group_entry(g)
        return {"groups": groups}

    # -- group declaration / join ------------------------------------------
    def declare_group(self, group_name: str, world_size: int, backend: str):
        self._groups[group_name] = {"world_size": world_size, "backend": backend}
        # a fresh declaration is an explicit re-init: clear the poison and
        # any stale state the aborted incarnation left behind
        if group_name in self._aborts:
            self._aborts.pop(group_name, None)
            self._clear_group_state(group_name)
        self._members.pop(group_name, None)
        # a re-declared group restarts its seq counters: stale pending
        # rounds can never complete and their keys would collide with the
        # new incarnation's first rounds (lag EWMAs survive — rank identity
        # is stable across re-inits, and the persistent-straggler score is
        # exactly the cross-restart signal)
        self._arrivals = {k: v for k, v in self._arrivals.items()
                          if not (isinstance(k, tuple) and k
                                  and k[0] == group_name)}
        return True

    def get_group(self, group_name: str):
        return self._groups.get(group_name)

    def join_member(self, group_name: str, rank: int, member: dict):
        """A rank announces its identity so the liveness monitor can abort
        the group promptly when this member dies or its node drains."""
        self._members.setdefault(group_name, {})[rank] = dict(member or {})
        self._ensure_monitor()
        return True

    def get_members(self, group_name: str) -> Dict[int, dict]:
        """rank -> {"actor_id", "node_id"} for a joined group — the
        topology source for the store backend's planner (ranks sharing a
        node form one latency domain)."""
        return dict(self._members.get(group_name, {}))

    def leave_group(self, group_name: str, rank: int):
        members = self._members.get(group_name)
        if members is not None:
            members.pop(rank, None)
            if not members:
                self._members.pop(group_name, None)
        return True

    # -- abort plumbing -----------------------------------------------------
    def abort_group(self, group_name: str, reason: str):
        """Poison the group: blocked waiters see the sentinel on their next
        poll, and the group's in-flight state is dropped so a re-init starts
        from a clean slate."""
        if group_name in self._aborts:
            return True
        self._aborts[group_name] = reason
        self._members.pop(group_name, None)
        self._clear_group_state(group_name)
        return True

    def get_abort(self, group_name: str) -> Optional[str]:
        return self._aborts.get(group_name)

    def _clear_group_state(self, group_name: str):
        """Drop gathers/barriers/p2p entries keyed by this group (every
        collective key is a tuple whose [0] is the group name)."""
        def _keep(key) -> bool:
            return not (isinstance(key, tuple) and key and key[0] == group_name)

        self._gathers = {k: v for k, v in self._gathers.items() if _keep(k)}
        self._gather_reads = {k: v for k, v in self._gather_reads.items() if _keep(k)}
        self._barriers = {k: v for k, v in self._barriers.items() if _keep(k)}
        self._barrier_reads = {k: v for k, v in self._barrier_reads.items() if _keep(k)}
        self._kv = {k: v for k, v in self._kv.items() if _keep(k)}
        self._arrivals = {k: v for k, v in self._arrivals.items() if _keep(k)}

    def _abort_for(self, key):
        """Sentinel when ``key`` belongs to a poisoned group, else None."""
        if not self._aborts:
            return None
        if isinstance(key, tuple) and key:
            reason = self._aborts.get(key[0])
            if reason is not None:
                return (ABORT_SENTINEL, reason)
        return None

    # -- liveness monitor ---------------------------------------------------
    def _ensure_monitor(self):
        if self._monitor_started:
            return
        try:
            from ray_tpu._private.worker import get_global_worker

            get_global_worker()  # only meaningful inside a live worker
        except Exception:  # noqa: BLE001 — unit tests instantiate the class
            return  # bare; they drive _check_members directly
        self._monitor_started = True
        threading.Thread(target=self._monitor_loop, daemon=True,
                         name="collective-store-monitor").start()

    def _monitor_loop(self):
        from ray_tpu._private.config import global_config
        from ray_tpu._private.worker import get_global_worker

        interval = global_config().collective_abort_poll_interval_s
        while True:
            time.sleep(interval)
            if not self._members:
                continue
            try:
                w = get_global_worker()
                nodes = w.gcs.call("GetAllNodeInfo", {},
                                   timeout=2, retry_deadline=0.0) or []
                actors = w.gcs.call("ListActors", {},
                                    timeout=2, retry_deadline=0.0) or []
            except Exception:  # noqa: BLE001 — GCS unreachable; retry
                continue
            try:
                node_states = {n["node_id"].hex(): n["state"] for n in nodes}
                actor_states = {a["actor_id"].hex(): a["state"]
                                for a in actors}
                self._check_members(node_states, actor_states)
            except Exception:  # noqa: BLE001 — the monitor must survive
                # races with join/leave mutations; a dead monitor would
                # silently restore the hang-to-timeout behavior
                logger.exception("collective store liveness check failed")

    def _check_members(self, node_states: Dict[str, str],
                       actor_states: Dict[str, str]):
        """Abort every group with a dead/restarting member or a member on a
        draining/dead node (pure: callable from tests with synthetic maps).
        Iterates over copies — join_member/leave_group mutate these dicts
        from the actor's RPC threads while the monitor thread scans."""
        for group_name, members in list(self._members.items()):
            for rank, m in list(members.items()):
                aid, nid = m.get("actor_id"), m.get("node_id")
                if aid is not None and actor_states.get(aid) in ("DEAD",
                                                                "RESTARTING"):
                    self.abort_group(
                        group_name,
                        f"rank {rank} (actor {aid[:8]}) is "
                        f"{actor_states[aid]}")
                    break
                if nid is not None and node_states.get(nid) in ("DRAINING",
                                                                "DEAD"):
                    self.abort_group(
                        group_name,
                        f"rank {rank}'s node {nid[:8]} is "
                        f"{node_states[nid]}")
                    break

    # -- plain KV (rendezvous) ---------------------------------------------
    def put(self, key, value):
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        self._kv[key] = value
        return True

    def get(self, key):
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        return self._kv.get(key)

    def pop(self, key):
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        return self._kv.pop(key, None)

    # -- gather: world_size ranks each contribute; all read; then GC -------
    def contribute(self, key: Tuple, rank: int, value,
                   arrival_rank=None, expected_ranks=None):
        """``rank`` keys the gathered value (a subrank inside hierarchical
        subgroup rounds); ``arrival_rank`` is the contributor's group-global
        rank for the arrival monitor, with ``expected_ranks`` naming the
        global ranks the round waits for — so diagnose/straggler EWMAs
        always speak global ranks."""
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        self._gathers.setdefault(key, {})[rank] = value
        self._stamp_arrival(key, arrival_rank if arrival_rank is not None
                            else rank, expected_ranks=expected_ranks)
        return True

    def collect(self, key: Tuple, world_size: int, reader_rank: int,
                expected_readers: Optional[int] = None):
        """Returns rank->value dict once all contributions are in, else None.
        Entry is deleted after every expected reader has read it —
        ``world_size`` readers by default; chunked-ring rounds have a
        single reader per chunk key (the chunk's owner) and pass
        ``expected_readers=1`` so their entries GC immediately."""
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        self._note_expected(key, world_size)
        entry = self._gathers.get(key)
        if entry is None or len(entry) < world_size:
            return None
        reads = self._gather_reads.setdefault(key, set())
        reads.add(reader_rank)
        result = entry
        if len(reads) >= (expected_readers or world_size):
            self._gathers.pop(key, None)
            self._gather_reads.pop(key, None)
        return result

    # -- barrier -----------------------------------------------------------
    def barrier_arrive(self, key: Tuple, rank: int, world_size: int):
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        arrived = self._barriers.setdefault(key, set())
        arrived.add(rank)
        self._stamp_arrival(key, rank, expected=world_size)
        return len(arrived) >= world_size

    def barrier_done(self, key: Tuple, rank: int, world_size: int):
        hit = self._abort_for(key)
        if hit is not None:
            return hit
        arrived = self._barriers.get(key)
        if arrived is None or len(arrived) < world_size:
            return False
        reads = self._barrier_reads.setdefault(key, set())
        reads.add(rank)
        if len(reads) >= world_size:
            self._barriers.pop(key, None)
            self._barrier_reads.pop(key, None)
        return True


def get_or_create_store():
    """Get the cluster-wide collective store actor, creating it if needed."""
    import ray_tpu

    try:
        return ray_tpu.get_actor(STORE_ACTOR_NAME)
    except Exception:  # noqa: BLE001 — no store yet: create below
        pass
    try:
        cls = ray_tpu.remote(_CollectiveStoreActor).options(
            name=STORE_ACTOR_NAME, lifetime="detached", num_cpus=0
        )
        return cls.remote()
    except Exception:  # noqa: BLE001
        # Lost the creation race; the winner's actor is registered by now.
        return ray_tpu.get_actor(STORE_ACTOR_NAME)


def check_abort(value):
    """Raise CollectiveAbortError when a store reply is the abort sentinel;
    otherwise pass the value through."""
    if is_abort(value):
        raise CollectiveAbortError(f"collective group aborted: {value[1]}")
    return value


def store_wait(store, method: str, args: tuple, timeout: Optional[float] = None,
               poll_interval: float = 0.002):
    """Poll a store method until it returns a non-None/True value.

    Raises CollectiveAbortError as soon as the group is poisoned (member
    death/drain) — promptly, not at the stock timeout."""
    import ray_tpu

    deadline = None if timeout is None else time.monotonic() + timeout
    interval = poll_interval
    while True:
        out = ray_tpu.get(getattr(store, method).remote(*args))
        if is_abort(out):
            raise CollectiveAbortError(f"collective group aborted: {out[1]}")
        if out is not None and out is not False:
            return out
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"collective store wait timed out on {method}{args}")
        time.sleep(interval)
        interval = min(interval * 1.5, 0.05)
