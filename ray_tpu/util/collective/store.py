"""Named store actor used for collective rendezvous + the STORE data plane.

reference: python/ray/util/collective/collective_group/nccl_collective_group.py:30-82
(Rendezvous via a named store actor holding the NCCLUniqueID; store name from
const.py get_store_name). Here the same pattern serves (a) publishing the
jax.distributed coordinator address for the XLA backend, and (b) the full
data plane for the STORE backend.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

STORE_ACTOR_NAME = "_ray_tpu_collective_store"


class _CollectiveStoreActor:
    """KV + barrier + gather primitives, garbage-collected by read counts."""

    def __init__(self):
        self._kv: Dict[Any, Any] = {}
        self._gathers: Dict[Tuple, Dict[int, Any]] = {}
        self._gather_reads: Dict[Tuple, set] = {}
        self._barriers: Dict[Tuple, set] = {}
        self._barrier_reads: Dict[Tuple, set] = {}
        self._groups: Dict[str, dict] = {}

    # -- group declaration / join ------------------------------------------
    def declare_group(self, group_name: str, world_size: int, backend: str):
        self._groups[group_name] = {"world_size": world_size, "backend": backend}
        return True

    def get_group(self, group_name: str):
        return self._groups.get(group_name)

    # -- plain KV (rendezvous) ---------------------------------------------
    def put(self, key, value):
        self._kv[key] = value
        return True

    def get(self, key):
        return self._kv.get(key)

    def pop(self, key):
        return self._kv.pop(key, None)

    # -- gather: world_size ranks each contribute; all read; then GC -------
    def contribute(self, key: Tuple, rank: int, value):
        self._gathers.setdefault(key, {})[rank] = value
        return True

    def collect(self, key: Tuple, world_size: int, reader_rank: int):
        """Returns rank->value dict once all contributions are in, else None.
        Entry is deleted after every rank has read it."""
        entry = self._gathers.get(key)
        if entry is None or len(entry) < world_size:
            return None
        reads = self._gather_reads.setdefault(key, set())
        reads.add(reader_rank)
        result = entry
        if len(reads) >= world_size:
            self._gathers.pop(key, None)
            self._gather_reads.pop(key, None)
        return result

    # -- barrier -----------------------------------------------------------
    def barrier_arrive(self, key: Tuple, rank: int, world_size: int) -> bool:
        arrived = self._barriers.setdefault(key, set())
        arrived.add(rank)
        return len(arrived) >= world_size

    def barrier_done(self, key: Tuple, rank: int, world_size: int) -> bool:
        arrived = self._barriers.get(key)
        if arrived is None or len(arrived) < world_size:
            return False
        reads = self._barrier_reads.setdefault(key, set())
        reads.add(rank)
        if len(reads) >= world_size:
            self._barriers.pop(key, None)
            self._barrier_reads.pop(key, None)
        return True


def get_or_create_store():
    """Get the cluster-wide collective store actor, creating it if needed."""
    import ray_tpu

    try:
        return ray_tpu.get_actor(STORE_ACTOR_NAME)
    except Exception:  # noqa: BLE001
        pass
    try:
        cls = ray_tpu.remote(_CollectiveStoreActor).options(
            name=STORE_ACTOR_NAME, lifetime="detached", num_cpus=0
        )
        return cls.remote()
    except Exception:  # noqa: BLE001
        # Lost the creation race; the winner's actor is registered by now.
        return ray_tpu.get_actor(STORE_ACTOR_NAME)


def store_wait(store, method: str, args: tuple, timeout: Optional[float] = None,
               poll_interval: float = 0.002):
    """Poll a store method until it returns a non-None/True value."""
    import ray_tpu

    deadline = None if timeout is None else time.monotonic() + timeout
    interval = poll_interval
    while True:
        out = ray_tpu.get(getattr(store, method).remote(*args))
        if out is not None and out is not False:
            return out
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"collective store wait timed out on {method}{args}")
        time.sleep(interval)
        interval = min(interval * 1.5, 0.05)
