"""Collective API over actors/tasks.

reference: python/ray/util/collective/collective.py — init_collective_group
:150, create_collective_group :187, allreduce :295, barrier :335, reduce
:348, broadcast :410, allgather :460, reducescatter :509, send/recv
:568,631; GroupManager :60 with backend dispatch :81-96.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.util.collective.types import Backend, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    """Process-local registry of the collective groups this process is in
    (reference: collective.py:60)."""

    def __init__(self):
        self._groups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create_group(self, backend: str, world_size: int, rank: int, group_name: str):
        backend = Backend.validate(backend)
        with self._lock:
            if group_name in self._groups:
                raise RuntimeError(f"collective group {group_name!r} already exists")
        if backend == Backend.XLA:
            from ray_tpu.util.collective.collective_group.xla_group import XLAGroup

            g = XLAGroup(world_size, rank, group_name)
        else:
            from ray_tpu.util.collective.collective_group.store_group import StoreGroup

            g = StoreGroup(world_size, rank, group_name)
        with self._lock:
            self._groups[group_name] = g
        return g

    def get_group(self, group_name: str):
        with self._lock:
            return self._groups.get(group_name)

    def destroy_group(self, group_name: str):
        with self._lock:
            g = self._groups.pop(group_name, None)
        if g is not None:
            g.destroy()


_group_mgr = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = Backend.STORE,
    group_name: str = "default",
    compression=None,
):
    """Join this process into a collective group; blocks until all ranks join
    (reference: collective.py:150).  ``compression`` sets the group-wide
    default ('int8', a CompressionSpec/dict, or None) — per-call
    ``compression=`` on an op overrides it; every member must pass the same
    value or ranks would disagree on the wire format."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    from ray_tpu.util.collective import compression as comp

    # validate BEFORE the blocking rendezvous: a bad spec must not leave a
    # registered group behind (is_group_initialized would say True and a
    # corrected retry would hit the stale group)
    spec = comp.resolve_spec(compression)
    g = _group_mgr.create_group(backend, world_size, rank, group_name)
    g.default_compression = spec
    return g


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = Backend.STORE,
    group_name: str = "default",
    compression=None,
):
    """Driver-side declarative setup (reference: collective.py:187): registers
    group metadata and invokes init on each actor via a hidden task, so actor
    code can call collective ops without its own init call.  ``compression``
    becomes the group default on every member (one declaration point, so
    ranks can't disagree on the wire format)."""
    import ray_tpu
    from ray_tpu.actor import ActorMethod
    from ray_tpu.util.collective import compression as comp
    from ray_tpu.util.collective.store import get_or_create_store

    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have equal length")
    spec = comp.resolve_spec(compression)  # validate on the driver, loudly
    store = get_or_create_store()
    ray_tpu.get(store.declare_group.remote(group_name, world_size, Backend.validate(backend)))
    refs = [
        ActorMethod(a, "__ray_tpu_call__").remote(
            _init_in_actor, world_size, r, backend, group_name, spec
        )
        for a, r in zip(actors, ranks)
    ]
    ray_tpu.get(refs)


def _init_in_actor(instance, world_size, rank, backend, group_name,
                   compression=None):
    init_collective_group(world_size, rank, backend=backend,
                          group_name=group_name, compression=compression)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.get_group(group_name) is not None


def destroy_collective_group(group_name: str = "default"):
    _group_mgr.destroy_group(group_name)


def get_rank(group_name: str = "default") -> int:
    g = _group_mgr.get_group(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _group_mgr.get_group(group_name)
    return g.world_size if g else -1


def _require_group(group_name: str):
    g = _group_mgr.get_group(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this process; "
            "call init_collective_group first"
        )
    return g


# -- per-op built-in telemetry (reference direction: PAPERS.md "Collective
# Communication for 100k+ GPUs" — straggler hunting needs per-op bytes /
# latency / bandwidth).  The payload size comes from the tensor's own
# ``nbytes`` (jax/numpy/torch all expose it) — never np.asarray(), which
# would COPY device arrays to host on the hot path.


def _tensor_meta(tensor):
    nbytes = getattr(tensor, "nbytes", None)
    if nbytes is None:
        try:
            import numpy as _np

            nbytes = _np.asarray(tensor).nbytes  # small host values only
        except Exception:  # noqa: BLE001
            nbytes = 0
    return int(nbytes or 0), str(getattr(tensor, "dtype", ""))


def _record_op(op: str, group, tensor, seconds: float):
    try:
        from ray_tpu._private import runtime_metrics

        nbytes, dtype = _tensor_meta(tensor) if tensor is not None else (0, "")
        backend = type(group).__name__.replace("Group", "").lower()
        runtime_metrics.record_collective(
            op, backend, group.world_size, nbytes, seconds, dtype)
    except Exception:  # noqa: BLE001 — telemetry must never fail a
        pass  # completed collective (the result is already computed)


def _trace_op(op: str, group, tensor, seconds: float, extra=None):
    """Span child of the active trace (serve request / task / user span) —
    per-op latency attribution on the causal timeline.  The guard is one
    thread-local read, so untraced ops pay ~nothing.  ``extra`` merges
    additional attributes (the compressed path's algorithm/wire figures)."""
    try:
        from ray_tpu.util import tracing

        if not tracing.context_active():
            return
        nbytes, dtype = _tensor_meta(tensor) if tensor is not None else (0, "")
        end = time.time()
        attributes = {"world_size": group.world_size, "nbytes": nbytes,
                      "dtype": dtype}
        if extra:
            attributes.update(extra)
        tracing.emit_span(
            f"collective:{op}", end - seconds, end, kind="collective",
            attributes=attributes)
    except Exception:  # noqa: BLE001 — telemetry must never fail an op
        pass


def _timed(op: str, group, tensor, fn):
    t0 = time.perf_counter()
    out = fn()
    dt = time.perf_counter() - t0
    _record_op(op, group, tensor, dt)
    _trace_op(op, group, tensor, dt)
    return out


def _record_compression(op: str, group, stats):
    """Book a compression-enabled op's logical-vs-wire accounting.  Called
    only when the backend filled last_op_stats — the stock path books
    nothing here, keeping compression-off metric output byte-identical."""
    try:
        from ray_tpu._private import runtime_metrics

        backend = type(group).__name__.replace("Group", "").lower()
        runtime_metrics.record_collective_compression(
            op, backend, group.world_size, group.group_name,
            stats.logical_bytes, stats.wire_bytes, stats.algorithm,
            stats.scheme, stats.quant_error, stats.inter_slice_bytes)
    except Exception:  # noqa: BLE001 — telemetry must never fail an op
        pass


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM,
              compression=None):
    """Allreduce ``tensor`` across the group.

    ``compression``: None inherits the group default; 'none' forces the
    stock path; 'int8' / a dict / a CompressionSpec enables the
    block-quantized and/or hierarchical algorithms for this call (large
    float SUM payloads only — everything else falls back untouched).
    """
    g = _require_group(group_name)
    spec = compression if compression is not None else g.default_compression
    if spec is None:
        return _timed("allreduce", g, tensor, lambda: g.allreduce(tensor, op))
    t0 = time.perf_counter()
    out = g.allreduce(tensor, op, compression=spec)
    dt = time.perf_counter() - t0
    _record_op("allreduce", g, tensor, dt)
    stats = g.last_op_stats
    if stats is not None:
        _record_compression("allreduce", g, stats)
        extra = {"algorithm": stats.algorithm, "scheme": stats.scheme,
                 "wire_bytes": stats.wire_bytes}
        if stats.quant_error >= 0.0:  # negative = unmeasured sentinel
            extra["quant_error"] = round(stats.quant_error, 6)
        _trace_op("allreduce", g, tensor, dt, extra=extra)
    else:
        _trace_op("allreduce", g, tensor, dt)
    return out


def allreduce_pytree(tree, group_name: str = "default",
                     op: ReduceOp = ReduceOp.SUM,
                     bucket_bytes: int = 4 << 20, compression=None):
    """Bucketed, pipelined allreduce of a whole gradient pytree (the
    trainer-path overlap: bucket k+1's round is issued while bucket k's
    result uploads).

    The tree partitions into size-targeted buckets (reverse
    materialization order — parallel/bucketing.py; deterministic, so all
    ranks issue identical sequences).  On the store backend the buckets
    ride ``StoreGroup.allreduce_bucketed`` (contributions fired without
    waiting); other backends fall back to per-bucket ``allreduce`` calls.
    ``compression`` composes per bucket (error-feedback residuals keyed
    per bucket).  Returns the reduced tree.
    """
    import numpy as np

    from ray_tpu.parallel.bucketing import (
        flatten_bucket,
        partition_buckets,
        unflatten_bucket,
    )

    g = _require_group(group_name)
    spec = compression if compression is not None else g.default_compression
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    arrays = [np.asarray(leaf) for leaf in leaves]
    buckets = partition_buckets(tree, bucket_bytes)
    payloads, metas = [], []
    for b in buckets:
        flat, splits = flatten_bucket(arrays, b)
        payloads.append(flat)
        metas.append((b, splits))
    t0 = time.perf_counter()
    if hasattr(g, "allreduce_bucketed"):
        reduced = g.allreduce_bucketed(payloads, op, compression=spec)
    else:
        reduced = [g.allreduce(p, op, compression=spec) for p in payloads]
    dt = time.perf_counter() - t0
    out = list(arrays)
    for flat, (b, splits) in zip(reduced, metas):
        for i, leaf in unflatten_bucket(flat, b, splits, arrays).items():
            out[i] = leaf
    total = int(sum(a.nbytes for a in arrays))
    _record_op("allreduce", g, None, dt)
    stats = getattr(g, "last_op_stats", None)
    if stats is not None:
        _record_compression("allreduce", g, stats)
        _trace_op("allreduce", g, None, dt,
                  extra={"algorithm": stats.algorithm,
                         "scheme": stats.scheme,
                         "wire_bytes": stats.wire_bytes,
                         "nbytes": total, "buckets": len(buckets)})
    else:
        _trace_op("allreduce", g, None, dt,
                  extra={"nbytes": total, "buckets": len(buckets)})
    return jax.tree.unflatten(treedef, out)


def plan_explain(nbytes: int, group_name: str = "default",
                 compression=None) -> dict:
    """Why would the planner pick what it picks for an ``nbytes`` payload
    on this group's real topology?  Returns the candidate cost table, the
    chosen algorithm, and the reason (see planner.plan_explain)."""
    g = _require_group(group_name)
    if hasattr(g, "plan_explain"):
        return g.plan_explain(nbytes, compression=compression)
    from ray_tpu.util.collective import compression as comp
    from ray_tpu.util.collective import planner as _planner

    spec = comp.resolve_spec(compression) or g.default_compression
    return _planner.plan_explain(
        nbytes, _planner.Topology.flat(g.world_size), spec)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    g = _require_group(group_name)
    return _timed("reduce", g, tensor, lambda: g.reduce(tensor, dst_rank, op))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _require_group(group_name)
    return _timed("broadcast", g, tensor, lambda: g.broadcast(tensor, src_rank))


def allgather(tensor, group_name: str = "default"):
    g = _require_group(group_name)
    return _timed("allgather", g, tensor, lambda: g.allgather(tensor))


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    g = _require_group(group_name)
    return _timed("reducescatter", g, tensor, lambda: g.reducescatter(tensor, op))


def barrier(group_name: str = "default"):
    g = _require_group(group_name)
    _timed("barrier", g, None, g.barrier)


def send(tensor, dst_rank: int, group_name: str = "default"):
    g = _require_group(group_name)
    _timed("send", g, tensor, lambda: g.send(tensor, dst_rank))


def recv(src_rank: int, group_name: str = "default"):
    g = _require_group(group_name)
    t0 = time.perf_counter()
    out = g.recv(src_rank)
    dt = time.perf_counter() - t0
    _record_op("recv", g, out, dt)
    _trace_op("recv", g, out, dt)
    return out
