"""Collective types: reduce ops, backend registry.

reference: python/ray/util/collective/types.py (ReduceOp, Backend).
"""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


class CollectiveAbortError(RuntimeError):
    """A pending collective was aborted because a group member died or its
    node began draining (preemption).  Raised within seconds instead of
    letting ``store_wait`` hang to its full timeout; the group stays
    poisoned — every subsequent op raises immediately — until the group is
    re-initialized (reference direction: fault-aware collectives, arxiv
    2510.20171)."""


class Backend:
    """Backend name constants (reference: collective.py:81-96 dispatch).

    The reference dispatches MPI/GLOO/NCCL/TORCH_GLOO; the TPU-native set is:

    - ``XLA``: jax.distributed process groups; data rides ICI/DCN via XLA
      collectives over a one-axis device mesh (the NCCL analog).
    - ``STORE``: named-store-actor rendezvous + object-store data plane —
      control-plane collectives that work anywhere (the gloo analog).
    """

    XLA = "xla"
    STORE = "store"

    @staticmethod
    def validate(name: str) -> str:
        name = str(name).lower()
        if name in ("nccl", "gloo", "torch_gloo", "mpi"):
            # GPU-era names map onto the TPU-native equivalents so reference
            # user code ports unchanged.
            return Backend.XLA if name == "nccl" else Backend.STORE
        if name not in (Backend.XLA, Backend.STORE):
            raise ValueError(f"unknown collective backend {name!r}")
        return name
