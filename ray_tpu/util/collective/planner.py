"""Topology-aware collective planner (TACCL-flavored, arxiv 2111.04867).

PR 3 shipped a two-way ``choose_plan`` branch (flat vs a fixed
intra→inter→intra hierarchy) with a sqrt-divisor slice guess.  This module
replaces it with a real planner over an explicit topology descriptor:

- :class:`Topology` records the per-rank latency-domain (TPU slice / host)
  ids and the link classes + calibrated α-β figures of the intra- and
  inter-domain links.  Backends build it from real metadata (device
  ``slice_index`` in ``XLAGroup``, group-member node identity in
  ``StoreGroup``) and refine the β terms with a one-shot link probe at
  group init, cached per group and refreshed on membership change.
- :func:`plan_allreduce` selects among ring / recursive-halving-doubling
  (tree) / 3-phase hierarchical / flat per (message size, world, link
  class) using the α-β cost model — the TACCL observation that the right
  schedule follows topology and message size, not a fixed hierarchy.
- :func:`plan_explain` is the debug surface: the candidate cost table, the
  winner, and the reason, for operators asking "why did it pick that".

Every decision is cached (plans are pure functions of hashable inputs) so
the hot-path cost of a repeated decision is one dict hit — budget-gated
under 5µs by test_perf_smoke.  Decisions are counted into
``ray_tpu_collective_plan_total{algorithm,reason}`` by the backends (only
when a compression spec is in force: the stock path books nothing, keeping
compression-off metric output byte-identical).

The slice-alignment rule (satellite of ISSUE 10): hierarchical schedules
group ranks into contiguous blocks, so they are only legal when the
topology's domains ARE contiguous equal-size rank blocks.  When they are
not (uneven slices, interleaved placement), the planner REFUSES the
hierarchy with reason ``unaligned_slices`` instead of silently running the
"ICI" phase over DCN — the exact failure mode of the old sqrt fallback.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

# link classes, fastest to slowest
LINK_ICI = "ici"    # intra-slice TPU interconnect
LINK_DCN = "dcn"    # inter-slice / inter-host datacenter network
LINK_HOST = "host"  # host loopback / store-actor relay (CPU test clusters)

# default α (per-message-step latency, seconds) and β⁻¹ (bandwidth,
# bytes/s) seeds per link class — deliberately coarse priors; the per-group
# probe replaces the bandwidth with a measured figure.  Ratios are what
# matter: ICI is ~10x DCN bandwidth at ~10x lower launch latency, and the
# store relay pays an actor round trip per step.
DEFAULT_ALPHA = {LINK_ICI: 1e-6, LINK_DCN: 2.5e-5, LINK_HOST: 4e-4}
DEFAULT_BANDWIDTH = {LINK_ICI: 4.0e10, LINK_DCN: 3.0e9, LINK_HOST: 1.0e9}

# recursive halving-doubling exchanges non-neighbor pairs, which share
# physical links on a torus/fat-tree: its bandwidth term pays a contention
# factor relative to the neighbor-only ring (the standard reason NCCL
# prefers rings at large sizes and trees at small ones)
TREE_CONTENTION = 2.0


def _default_alpha(link: str) -> float:
    return DEFAULT_ALPHA.get(link, DEFAULT_ALPHA[LINK_HOST])


def _default_bw(link: str) -> float:
    return DEFAULT_BANDWIDTH.get(link, DEFAULT_BANDWIDTH[LINK_HOST])


@dataclasses.dataclass(frozen=True)
class Topology:
    """Explicit collective topology: who sits where, over which links.

    slice_ids:  per-rank latency-domain id (TPU slice index, or host/node
                identity for the store backend), length == world_size.
    intra_link / inter_link: link class names (for explain/metrics).
    intra_bw / inter_bw: measured or default bandwidth, bytes/s.
    intra_alpha / inter_alpha: per-step launch latency, seconds.
    version:    bumped on membership change — plan caches key on it, so a
                refreshed probe invalidates stale decisions.
    """

    world_size: int
    slice_ids: Tuple[int, ...] = ()
    intra_link: str = LINK_HOST
    inter_link: str = LINK_DCN
    intra_bw: float = DEFAULT_BANDWIDTH[LINK_HOST]
    inter_bw: float = DEFAULT_BANDWIDTH[LINK_DCN]
    intra_alpha: float = DEFAULT_ALPHA[LINK_HOST]
    inter_alpha: float = DEFAULT_ALPHA[LINK_DCN]
    version: int = 0

    def __post_init__(self):
        if self.slice_ids and len(self.slice_ids) != self.world_size:
            raise ValueError(
                f"slice_ids length {len(self.slice_ids)} != world_size "
                f"{self.world_size}")

    @classmethod
    def flat(cls, world_size: int, link: str = LINK_HOST, **kw) -> "Topology":
        """Single latency domain (one slice / one host / CPU tests)."""
        kw.setdefault("intra_link", link)
        kw.setdefault("intra_bw", _default_bw(link))
        kw.setdefault("intra_alpha", _default_alpha(link))
        return cls(world_size=world_size,
                   slice_ids=tuple([0] * world_size), **kw)

    @classmethod
    def from_slice_ids(cls, slice_ids, intra_link: str = LINK_ICI,
                       inter_link: str = LINK_DCN, **kw) -> "Topology":
        """Real topology from per-rank domain ids (device slice_index /
        member node identity), normalized to small ints in first-seen
        order so equal layouts hash equal."""
        seen: Dict[object, int] = {}
        norm = []
        for s in slice_ids:
            if s not in seen:
                seen[s] = len(seen)
            norm.append(seen[s])
        kw.setdefault("intra_bw", _default_bw(intra_link))
        kw.setdefault("inter_bw", _default_bw(inter_link))
        kw.setdefault("intra_alpha", _default_alpha(intra_link))
        kw.setdefault("inter_alpha", _default_alpha(inter_link))
        return cls(world_size=len(norm), slice_ids=tuple(norm),
                   intra_link=intra_link, inter_link=inter_link, **kw)

    @property
    def num_slices(self) -> int:
        return len(set(self.slice_ids)) if self.slice_ids else 1

    def slice_groups(self) -> Dict[int, Tuple[int, ...]]:
        """domain id -> ranks in that domain."""
        groups: Dict[int, list] = {}
        for rank, sid in enumerate(self.slice_ids):
            groups.setdefault(sid, []).append(rank)
        return {sid: tuple(rs) for sid, rs in groups.items()}

    def aligned_slice_size(self) -> Optional[int]:
        """Members per slice IF the domains form contiguous equal-size rank
        blocks (the layout every hierarchical schedule assumes: rank r is
        member r%ss of slice r//ss).  None when they don't — the caller
        must refuse the hierarchy rather than run an "intra" phase across
        a real domain boundary."""
        if not self.slice_ids or self.num_slices <= 1:
            return None
        if self.world_size % self.num_slices != 0:
            return None
        ss = self.world_size // self.num_slices
        for rank, sid in enumerate(self.slice_ids):
            if sid != self.slice_ids[(rank // ss) * ss]:
                return None
            if rank % ss and sid != self.slice_ids[rank - 1]:
                return None
        return ss

    def slice_aligned(self, slice_size: int) -> bool:
        """True when partitioning ranks into contiguous ``slice_size``
        blocks never puts two domains inside one block.  A single-domain
        topology is aligned for ANY valid partition (there is no boundary
        to violate — explicit slice_size hierarchies on one host stay
        legal, as before)."""
        if slice_size <= 0 or self.world_size % slice_size:
            return False
        if self.num_slices <= 1:
            return True
        for start in range(0, self.world_size, slice_size):
            block = self.slice_ids[start:start + slice_size]
            if len(set(block)) != 1:
                return False
        return True


def topology_for_devices(devices, intra_link: Optional[str] = None) -> Topology:
    """Topology for an in-program device group — e.g. one mesh axis of a
    tensor-parallel serving engine.  Latency domains come from each
    device's ``slice_index`` (TPU multislice) falling back to
    ``process_index``; the intra link defaults to ICI when every member is
    a TPU and host loopback otherwise (CPU test meshes), so the α-β model
    prices decode's small latency-bound collectives on the link class
    they actually cross."""
    devs = list(devices)
    if intra_link is None:
        intra_link = (LINK_ICI if devs and all(
            getattr(d, "platform", "") == "tpu" for d in devs)
            else LINK_HOST)
    sids = []
    for d in devs:
        sid = getattr(d, "slice_index", None)
        if sid is None:
            sid = getattr(d, "process_index", 0)
        sids.append(sid)
    return Topology.from_slice_ids(sids or (0,), intra_link=intra_link)


# ---------------------------------------------------------------------------
# α-β cost model.  t(algorithm) = steps·α + bytes_on_slowest_link·β.  The
# model only needs to ORDER the candidates correctly per regime; absolute
# seconds are not a promise (plan_explain labels them "modeled").
# ---------------------------------------------------------------------------


def _cost_flat(nbytes: int, t: Topology) -> float:
    """Direct exchange / single fused op: one step, every rank receives
    (n-1) payloads over its link (the store full-gather shape; XLA's stock
    psum is better than this, but flat is only ever chosen when the
    message is too small for decomposition to pay)."""
    n = t.world_size
    alpha, bw = _slowest(t)
    return alpha + (n - 1) * nbytes / bw


def _cost_ring(nbytes: int, t: Topology) -> float:
    """Bandwidth-optimal ring (reduce-scatter + allgather): 2(n-1) steps,
    2(n-1)/n · S per link."""
    n = t.world_size
    alpha, bw = _slowest(t)
    return 2 * (n - 1) * alpha + 2 * (n - 1) / n * nbytes / bw


def _cost_tree(nbytes: int, t: Topology) -> float:
    """Recursive halving-doubling: 2·log2(n) steps at ring-equal volume,
    but non-neighbor pairs pay the contention factor.  Infinite for
    non-power-of-two worlds (the schedule needs clean halving)."""
    n = t.world_size
    if n & (n - 1):
        return float("inf")
    alpha, bw = _slowest(t)
    log2n = n.bit_length() - 1
    return (2 * log2n * alpha
            + TREE_CONTENTION * 2 * (n - 1) / n * nbytes / bw)


def _cost_hierarchical(nbytes: int, t: Topology, slice_size: int) -> float:
    """3-phase: intra reduce-scatter + allgather (ring-shaped, fast link)
    and the 1/slice_size shard exchanged across domains (slow link)."""
    n = t.world_size
    ss = max(slice_size, 1)
    nslices = n // ss
    shard = nbytes / ss
    intra = (2 * (ss - 1) * t.intra_alpha
             + 2 * (ss - 1) / max(ss, 1) * nbytes / t.intra_bw)
    inter = (t.inter_alpha
             + (nslices - 1) / max(nslices, 1) * shard * 2 / t.inter_bw)
    return intra + inter


def _slowest(t: Topology) -> Tuple[float, float]:
    """(α, bw) of the slowest link the group spans — what a non-topology-
    aware (flat/ring/tree over all ranks) schedule is bound by."""
    if t.num_slices > 1:
        return (max(t.intra_alpha, t.inter_alpha),
                min(t.intra_bw, t.inter_bw))
    return t.intra_alpha, t.intra_bw


_COSTS = {
    "flat": _cost_flat,
    "ring": _cost_ring,
    "tree": _cost_tree,
}


# ---------------------------------------------------------------------------
# Planner proper
# ---------------------------------------------------------------------------

# decision cache: plans are pure in (nbytes, world, topology, spec,
# allowed); topology.version folds membership/probe refreshes into the key
_PLAN_CACHE: Dict[Tuple, object] = {}
_PLAN_CACHE_MAX = 4096


def _resolve_hierarchy(topology: Topology, spec) -> Tuple[int, str]:
    """(slice_size, reason): slice_size <= 1 means the hierarchy is
    refused, with the reason naming why (counted into the plan metric)."""
    world = topology.world_size
    want = spec.slice_size
    if want is not None:
        if not (1 < want < world) or world % want:
            return 1, "invalid_slice_size"
        if not topology.slice_aligned(want):
            return 1, "unaligned_slices"
        return want, "explicit_slice_size"
    ss = topology.aligned_slice_size()
    if ss is None:
        if topology.num_slices > 1:
            # a real multi-domain topology whose domains are uneven or
            # interleaved: the old sqrt fallback would happily group
            # ranks across the boundary and run "ICI" phases over DCN
            return 1, "unaligned_slices"
        return 1, "single_slice"
    if ss <= 1 or ss >= world:
        return 1, "degenerate_slices"
    return ss, "dcn_boundary"


def plan_allreduce(nbytes: int, topology: Topology, spec, *,
                   allowed: Optional[Tuple[str, ...]] = None):
    """The planner: one Plan per (message size, topology, spec).

    ``allowed`` names the algorithms the calling backend implements
    (default: all).  Returns a :class:`compression.Plan` whose ``reason``
    explains the decision; ``plan.is_stock`` keeps its PR-3 meaning (take
    the exact pre-compression code path).
    """
    from ray_tpu.util.collective import compression as comp

    key = (nbytes, topology, spec, allowed)
    try:
        hit = _PLAN_CACHE.get(key)
    except TypeError:  # unhashable caller-supplied spec subclass — plan raw
        hit = None
        key = None
    if hit is not None:
        return hit
    plan = _plan_uncached(nbytes, topology, spec, allowed, comp)
    if key is not None:
        if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
            _PLAN_CACHE.clear()
        _PLAN_CACHE[key] = plan
    return plan


def _plan_uncached(nbytes, topology, spec, allowed, comp):
    def stock(reason):
        return dataclasses.replace(comp._STOCK_PLAN, reason=reason)

    if spec is None:
        return stock("no_spec")
    if topology.world_size <= 1:
        return stock("solo")
    if nbytes < spec.min_bytes:
        return stock("below_min_bytes")
    if allowed is None:
        allowed = (comp.ALG_FLAT, comp.ALG_RING, comp.ALG_TREE,
                   comp.ALG_HIERARCHICAL)
    scheme = spec.scheme

    # -- hierarchy resolution (topology-gated, never a divisor guess) ------
    hier = spec.hierarchical
    refusal = ""
    slice_size = 1
    if hier is None:
        hier = topology.num_slices > 1 or spec.slice_size is not None
    elif hier is False and scheme == comp.SCHEME_NONE:
        # resolve_spec("none"): scheme none + hierarchical False is the
        # documented force-stock escape hatch — no codec, no algorithm
        # planning, byte-identical to compression-off.  (scheme none with
        # hierarchical=None still gets ring/tree planning below.)
        return stock("forced_stock")
    if hier:
        slice_size, why = _resolve_hierarchy(topology, spec)
        if slice_size <= 1:
            hier = False
            refusal = why
    if hier and comp.ALG_HIERARCHICAL in allowed:
        return comp.Plan(comp.ALG_HIERARCHICAL, scheme, slice_size, spec,
                         reason=("explicit_slice_size"
                                 if spec.slice_size is not None
                                 else "dcn_boundary"))

    # -- flat-topology (or hierarchy-refused) algorithm choice -------------
    if scheme == comp.SCHEME_INT8:
        # the EQuARX two-phase program IS the bandwidth-optimal quantized
        # schedule (all_to_all + all_gather ≈ ring volume at 1/4 bytes);
        # there is no quantized ring/tree variant to trade against
        return comp.Plan(comp.ALG_FLAT, scheme, 1, spec,
                         reason=refusal or "quantized_two_phase")
    costs = {alg: fn(nbytes, topology) for alg, fn in _COSTS.items()
             if alg in allowed}
    if not costs:
        return stock(refusal or "no_algorithm")
    best = min(costs, key=costs.get)
    if best == comp.ALG_FLAT:
        return stock(refusal or "latency_bound")
    reason = refusal or (
        "latency_bound" if best == comp.ALG_TREE else "bandwidth_bound")
    return comp.Plan(best, comp.SCHEME_NONE, 1, spec, reason=reason)


def plan_explain(nbytes: int, topology: Topology, spec, *,
                 allowed: Optional[Tuple[str, ...]] = None) -> dict:
    """Debug surface: the full candidate table behind one decision.

    Returns {chosen, reason, scheme, slice_size, topology:{...},
    modeled_cost_s:{algorithm: seconds}} — costs are the α-β model's
    ordering device, not a latency promise.
    """
    from ray_tpu.util.collective import compression as comp

    plan = plan_allreduce(nbytes, topology, spec, allowed=allowed)
    costs = {alg: fn(nbytes, topology) for alg, fn in _COSTS.items()}
    ss = topology.aligned_slice_size()
    if ss:
        costs[comp.ALG_HIERARCHICAL] = _cost_hierarchical(nbytes, topology, ss)
    return {
        "nbytes": int(nbytes),
        "chosen": plan.algorithm,
        "scheme": plan.scheme,
        "slice_size": plan.slice_size,
        "reason": plan.reason,
        "is_stock": plan.is_stock,
        "topology": {
            "world_size": topology.world_size,
            "num_slices": topology.num_slices,
            "aligned_slice_size": ss,
            "intra_link": topology.intra_link,
            "inter_link": topology.inter_link,
            "intra_bw_gbps": round(topology.intra_bw / 1e9, 3),
            "inter_bw_gbps": round(topology.inter_bw / 1e9, 3),
            "version": topology.version,
        },
        "modeled_cost_s": {a: (None if c == float("inf") else round(c, 9))
                           for a, c in sorted(costs.items())},
    }


def record_plan(algorithm: str, reason: str) -> None:
    """Book one plan decision (backends call this only when a compression
    spec is in force — the stock no-spec path must book nothing)."""
    try:
        from ray_tpu._private import runtime_metrics

        runtime_metrics.inc_collective_plan(algorithm, reason)
    except Exception:  # noqa: BLE001 — telemetry must never fail an op
        pass
