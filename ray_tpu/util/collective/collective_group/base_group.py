"""Collective group ABC.

reference: python/ray/util/collective/collective_group/base_collective_group.py.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List

from ray_tpu.util.collective.types import ReduceOp


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name
        # per-group compression default (set by init_collective_group); a
        # per-call compression= overrides.  None = stock uncompressed path.
        self.default_compression = None
        # OpStats of the most recent compression-enabled op (None when the
        # stock path ran) — read by the API layer for metrics/spans.
        self.last_op_stats = None
        # host-side op counter for flight-recorder entry/exit marks: the
        # hang sweep compares members' last-entered (op, seq) to name the
        # member that never arrived
        self._fr_seq = 0

    def _mark(self, op: str, phase: str, seq: int = None):
        """Flight-recorder collective mark: (group, op, seq, member rank).
        ``enter`` is recorded BEFORE the op blocks, so a member wedged
        inside the collective still shows where it is."""
        from ray_tpu._private import flight_recorder

        if seq is None:
            if phase == "enter":
                self._fr_seq += 1
            seq = self._fr_seq
        flight_recorder.record(
            "collective", f"{self._group_name}:{op}",
            f"{phase}:seq{seq}:rank{self._rank}/{self._world_size}")
        return seq

    def _topology_num_slices(self) -> int:
        """How many latency domains (TPU slices / hosts) the group spans —
        drives the hierarchical-algorithm auto policy.  Backends with real
        topology knowledge override."""
        return 1

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    def destroy(self):  # noqa: B027
        from ray_tpu.util.collective import compression

        compression.error_feedback.clear_group(self._group_name)

    @abstractmethod
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM,
                  compression=None): ...

    @abstractmethod
    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM): ...

    @abstractmethod
    def broadcast(self, tensor, src_rank: int = 0): ...

    @abstractmethod
    def allgather(self, tensor) -> List[Any]: ...

    @abstractmethod
    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM): ...

    @abstractmethod
    def barrier(self): ...

    @abstractmethod
    def send(self, tensor, dst_rank: int): ...

    @abstractmethod
    def recv(self, src_rank: int): ...
