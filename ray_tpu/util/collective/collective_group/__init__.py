from ray_tpu.util.collective.collective_group.base_group import BaseGroup

__all__ = ["BaseGroup"]
