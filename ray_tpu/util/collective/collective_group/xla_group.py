"""XLA backend: process-level collectives riding ICI/DCN via XLA.

The NCCL analog (reference: nccl_collective_group.py — cupy NCCL comms with
Rendezvous via a named store actor :30-82). TPU-native design: the store
actor publishes the jax.distributed coordinator address (instead of an
ncclUniqueId); every member calls jax.distributed.initialize; collective ops
are jitted shard_map programs over a one-axis mesh with ONE device per
member process, so XLA lowers them to ICI collectives inside a slice and
DCN collectives across slices.
"""

from __future__ import annotations

import socket
import time
from typing import Any, List

import numpy as np

from ray_tpu.util.collective import compression as comp
from ray_tpu.util.collective import planner as topo_planner
from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.store import get_or_create_store, store_wait
from ray_tpu.util.collective.types import ReduceOp

_PSUM_OPS = {
    ReduceOp.SUM: "psum",
    ReduceOp.MAX: "pmax",
    ReduceOp.MIN: "pmin",
}


from ray_tpu.util.jax_compat import shard_map as _shard_map  # noqa: E402


def _shard_map_unchecked(f, **kw):
    """shard_map without replication checking: the quantized/hierarchical
    programs end in all_gathers whose outputs are replicated in VALUE but
    not provably so to check_rep, so the checker must be off for out_specs
    P().  Older/newer jax spell the flag differently; fall back to the
    checked path if neither spelling exists."""
    for flag in ("check_rep", "check_vma"):
        try:
            return _shard_map(f, **kw, **{flag: False})
        except TypeError:
            continue
    return _shard_map(f, **kw)


def build_quantized_allreduce(mesh, axis_name: str, world_size: int,
                              block_size: int = comp.DEFAULT_BLOCK_SIZE,
                              accum_dtype: str = "bfloat16"):
    """EQuARX-style two-phase quantized allreduce as a jitted shard_map
    program (arxiv 2506.17615): the wire collectives (all_to_all for the
    reduce-scatter phase, all_gather for the broadcast phase) carry int8
    codes + per-block float32 scales; accumulation happens dequantized in
    ``accum_dtype`` (bf16 per the paper).

    Inputs are the stacked global arrays (codes [world, n] int8 and scales
    [world, n/bs] float32, both sharded along ``axis_name``) with
    ``n % (world_size * block_size) == 0``; output is the reduced [n]
    float32, identical on every rank.  Exposed at module level so tests
    can drive it over a multi-device CPU mesh directly.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    acc_dt = jnp.dtype(accum_dtype)

    def body(codes_row, scales_row):
        # codes_row: [1, n] int8, scales_row: [1, n/bs] f32 (this rank's row)
        c, s = codes_row[0], scales_row[0]
        n = c.shape[0]
        shard = n // world_size
        shard_nb = s.shape[0] // world_size
        # phase 1 (reduce-scatter): all_to_all so every rank receives all
        # ranks' codes for ITS shard — int8 on the wire
        ca = jax.lax.all_to_all(c.reshape(world_size, shard), axis_name,
                                split_axis=0, concat_axis=0, tiled=True)
        sa = jax.lax.all_to_all(s.reshape(world_size, shard_nb), axis_name,
                                split_axis=0, concat_axis=0, tiled=True)
        # dequantize contributions, accumulate in accum_dtype (EQuARX: bf16)
        blocks = (ca.reshape(world_size, shard_nb, block_size)
                  .astype(jnp.float32) * sa[:, :, None])
        red = jnp.sum(blocks.astype(acc_dt), axis=0).astype(jnp.float32)
        # phase 2 (allgather): requantize the reduced shard, gather int8
        c2, s2 = comp.jnp_quantize_blocks(red.reshape(shard), block_size)
        cg = jax.lax.all_gather(c2, axis_name, axis=0, tiled=True)
        sg = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
        return comp.jnp_dequantize_blocks(cg, sg, block_size)

    return jax.jit(_shard_map_unchecked(
        body, mesh=mesh, in_specs=(P(axis_name), P(axis_name)),
        out_specs=P()))


def build_hierarchical_allreduce(mesh2d, num_slices: int, slice_size: int,
                                 scheme: str = comp.SCHEME_NONE,
                                 block_size: int = comp.DEFAULT_BLOCK_SIZE,
                                 accum_dtype: str = "bfloat16"):
    """Hierarchical allreduce over a (slice, intra) mesh: intra-slice
    reduce-scatter (ICI), inter-slice exchange on 1/slice_size shards (the
    DCN phase — optionally int8-quantized), intra-slice allgather.

    Input is the stacked global float32 [num_slices, slice_size, n] sharded
    over both axes, ``n % (slice_size * block_size) == 0``; output is the
    reduced [n] float32, identical on every rank.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    acc_dt = jnp.dtype(accum_dtype)

    def body(x):
        # x: [1, 1, n] — this rank's payload
        v = x[0, 0]
        # phase 1: intra-slice reduce-scatter over ICI (full precision)
        shard = jax.lax.psum_scatter(v, "intra", scatter_dimension=0,
                                     tiled=True)
        if scheme == comp.SCHEME_INT8 and num_slices > 1:
            # phase 2 (DCN): quantize the shard, gather codes across
            # slices, accumulate dequantized in accum_dtype
            c, s = comp.jnp_quantize_blocks(shard, block_size)
            cg = jax.lax.all_gather(c, "slice", axis=0, tiled=False)
            sg = jax.lax.all_gather(s, "slice", axis=0, tiled=False)
            blocks = (cg.reshape(num_slices, -1, block_size)
                      .astype(jnp.float32) * sg[:, :, None])
            shard = jnp.sum(blocks.astype(acc_dt),
                            axis=0).astype(jnp.float32).reshape(shard.shape)
        else:
            shard = jax.lax.psum(shard, "slice")
        # phase 3: intra-slice allgather over ICI
        return jax.lax.all_gather(shard, "intra", axis=0, tiled=True)

    return jax.jit(_shard_map_unchecked(
        body, mesh=mesh2d, in_specs=P("slice", "intra"), out_specs=P()))


def build_ring_allreduce(mesh, axis_name: str, world_size: int):
    """Bandwidth-optimal ring decomposition as an explicit program:
    reduce-scatter (psum_scatter — XLA lowers it to the neighbor ring) then
    all_gather.  2(n-1) neighbor steps moving 2(n-1)/n·S per link — the
    large-message winner on every link class.

    Input is the stacked [world, n] float payload sharded along
    ``axis_name`` with ``n % world_size == 0`` (pad host-side); output is
    the reduced [n], identical on every rank.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    def body(x):
        v = x[0]  # [n] — this rank's payload
        shard = jax.lax.psum_scatter(v, axis_name, scatter_dimension=0,
                                     tiled=True)
        return jax.lax.all_gather(shard, axis_name, axis=0, tiled=True)

    return jax.jit(_shard_map_unchecked(
        body, mesh=mesh, in_specs=(P(axis_name),), out_specs=P()))


def build_tree_allreduce(mesh, axis_name: str, world_size: int):
    """Recursive halving-doubling ("tree"): log2(n) pairwise-exchange
    rounds of halving payloads (reduce-scatter), then log2(n) doubling
    rounds (allgather).  Latency 2·log2(n)·α vs the ring's 2(n-1)·α — the
    small-message winner; its non-neighbor pairs pay link contention at
    size, which the planner's cost model charges.

    Power-of-two worlds only (the planner never selects tree otherwise).
    Input/output contract matches :func:`build_ring_allreduce`.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if world_size & (world_size - 1):
        raise ValueError(
            f"tree allreduce needs a power-of-two world, got {world_size}")

    def body(x):
        v = x[0]  # [n], n % world_size == 0
        idx = jax.lax.axis_index(axis_name)
        cur = v
        # phase 1 — reduce-scatter by recursive halving: at mask m, keep
        # the half matching your bit (MSB first), send the other to the
        # partner rank^m, add what it sent you.  After all rounds rank r
        # holds the reduced segment r (bits MSB->LSB spell the offset).
        mask = world_size // 2
        perms = []
        while mask >= 1:
            perms.append([(i, i ^ mask) for i in range(world_size)])
            mask //= 2
        for perm in perms:
            m = (perm[0][0] ^ perm[0][1])
            half = cur.shape[0] // 2
            lo, hi = cur[:half], cur[half:]
            bit = (idx & m) != 0
            send = jnp.where(bit, lo, hi)
            keep = jnp.where(bit, hi, lo)
            recv = jax.lax.ppermute(send, axis_name, perm)
            cur = keep + recv
        # phase 2 — allgather by recursive doubling (reverse masks):
        # concatenate in bit order so segments land back in sequence
        for perm in reversed(perms):
            m = (perm[0][0] ^ perm[0][1])
            bit = (idx & m) != 0
            recv = jax.lax.ppermute(cur, axis_name, perm)
            cur = jnp.where(bit, jnp.concatenate([recv, cur]),
                            jnp.concatenate([cur, recv]))
        return cur

    return jax.jit(_shard_map_unchecked(
        body, mesh=mesh, in_specs=(P(axis_name),), out_specs=P()))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _host_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class XLAGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        import jax

        self._ensure_process_group(world_size, rank, group_name)
        # One device per member process: the collective contract is
        # process-granular (each member contributes one tensor).
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) < world_size:
            if world_size == 1:
                by_proc = {0: jax.devices()[0]}
            else:
                raise RuntimeError(
                    f"xla group needs {world_size} jax processes, found {len(by_proc)}"
                )
        self._devices = [by_proc[p] for p in sorted(by_proc)[:world_size]]
        self._mesh = jax.sharding.Mesh(np.array(self._devices), ("world",))
        self._local_device = by_proc.get(jax.process_index(), self._devices[0])
        # per-instance program cache (NOT functools.lru_cache on methods —
        # that pins self and its Mesh forever, VERDICT r1 weak #4)
        self._fn_cache = {}
        # explicit topology descriptor for the planner: per-rank slice ids
        # from device metadata, link bandwidth refined by a one-shot probe.
        # Built LAZILY on the first planner use — only spec-in-force calls
        # read it, and the probe compiles a small psum the stock path never
        # needs (a no-spec group's init must not pay a compile).  Cached
        # for the group's lifetime; XLA membership is fixed, a re-init
        # builds a fresh group and re-probes.
        self._topology = None

    def _build_topology(self) -> topo_planner.Topology:
        """Topology from the real device list: ``slice_index`` is the
        latency-domain id (multislice TPU pods report it; CPU/single-slice
        devices collapse to one domain), the platform picks the link
        class, and a one-shot probe calibrates the intra-link β term."""
        slice_ids = tuple(
            getattr(d, "slice_index", None) or 0 for d in self._devices)
        on_tpu = getattr(self._devices[0], "platform", "cpu") == "tpu"
        intra = topo_planner.LINK_ICI if on_tpu else topo_planner.LINK_HOST
        kw = {}
        bw = self._probe_link_bandwidth()
        if bw is not None:
            kw["intra_bw"] = bw
        return topo_planner.Topology.from_slice_ids(
            slice_ids, intra_link=intra, inter_link=topo_planner.LINK_DCN,
            **kw)

    def _probe_link_bandwidth(self):
        """One-shot link probe at group init: time a small psum over the
        group mesh and derive effective bus bandwidth (bytes/s).  Collective
        — every member runs it inside its own __init__, which is already
        a synchronized rendezvous.  Solo groups (and any probe failure)
        fall back to the planner's per-class defaults."""
        if self._world_size <= 1:
            return None
        try:
            n = 8192  # 32 KiB/rank: big enough to measure, sub-ms to move
            arr = np.ones(n, np.float32)
            fn = self._allreduce_fn(_PSUM_OPS[ReduceOp.SUM])
            garr = self._global_stack(arr)
            import jax

            jax.block_until_ready(fn(garr))  # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(fn(garr))
            dt = time.perf_counter() - t0
            if dt <= 0:
                return None
            w = self._world_size
            return 2 * (w - 1) / w * arr.nbytes / dt
        except Exception:  # noqa: BLE001 — probe is advisory, never fatal
            return None

    @staticmethod
    def _ensure_process_group(world_size: int, rank: int, group_name: str):
        """Rendezvous + jax.distributed.initialize (idempotent)."""
        import jax

        if world_size <= 1 or jax.process_count() >= world_size:
            return  # single process, or runtime already spans the group
        store = get_or_create_store()
        key = (group_name, "xla_coordinator")
        if rank == 0:
            import ray_tpu

            addr = f"{_host_ip()}:{_free_port()}"
            ray_tpu.get(store.put.remote(key, addr))
        else:
            addr = store_wait(store, "get", (key,))
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=world_size, process_id=rank
        )

    # -- jitted collective programs (cached per op in a per-instance dict) --
    def _allreduce_fn(self, op_name: str):
        fn = self._fn_cache.get(("allreduce", op_name))
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            def body(x):
                # x: [1, ...] local row of the stacked [world, ...] array
                return getattr(jax.lax, op_name)(x, "world")[0]

            fn = jax.jit(
                _shard_map(body, mesh=self._mesh, in_specs=P("world"), out_specs=P())
            )
            self._fn_cache[("allreduce", op_name)] = fn
        return fn

    def _reducescatter_fn(self, op_name: str):
        fn = self._fn_cache.get(("reducescatter", op_name))
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            def body(x):
                # x: [1, ...] local row; output: this rank's reduced shard
                summed = getattr(jax.lax, op_name)(x, "world")[0]
                shard = summed.shape[0] // self._world_size
                idx = jax.lax.axis_index("world")
                return jax.lax.dynamic_slice_in_dim(summed, idx * shard, shard, axis=0)

            fn = jax.jit(
                _shard_map(body, mesh=self._mesh, in_specs=P("world"), out_specs=P("world"))
            )
            self._fn_cache[("reducescatter", op_name)] = fn
        return fn

    def _global_stack(self, arr):
        """Global [world, ...] array whose rank-th row is this process's arr."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = jax.device_put(arr[None, ...], self._local_device)
        sharding = NamedSharding(self._mesh, P("world"))
        return jax.make_array_from_single_device_arrays(
            (self._world_size, *arr.shape), sharding, [local]
        )

    def _local_shard(self, garr):
        """This process's shard of a 'world'-sharded global array."""
        shards = [s for s in garr.addressable_shards if s.device == self._local_device]
        return np.asarray(shards[0].data)

    # -- collectives --------------------------------------------------------
    def _reduce_impl(self, tensor, op: ReduceOp):
        import jax

        if op == ReduceOp.PRODUCT:
            # no pprod in lax; log-space or gather-reduce. Gather-reduce:
            rows = self.allgather(tensor)
            out = rows[0]
            for r in rows[1:]:
                out = out * r
            return out
        arr = np.asarray(tensor)
        garr = self._global_stack(arr)
        out = self._allreduce_fn(_PSUM_OPS[op])(garr)
        local = [s for s in out.addressable_shards if s.device == self._local_device]
        return np.asarray(local[0].data) if local else np.asarray(jax.device_get(out))

    def _topology_num_slices(self) -> int:
        """Distinct TPU slices the group's devices sit on (drives the
        hierarchical auto policy; 1 on CPU / single-slice)."""
        return self.topology().num_slices

    def topology(self) -> topo_planner.Topology:
        if self._topology is None:
            self._topology = self._build_topology()
        return self._topology

    def plan_explain(self, nbytes: int, compression=None) -> dict:
        """Debug surface: the planner's candidate table for a payload of
        ``nbytes`` on this group's real topology."""
        spec = comp.resolve_spec(compression)
        if spec is None:
            spec = self.default_compression
        return topo_planner.plan_explain(nbytes, self.topology(), spec,
                                         allowed=self._PLANNABLE)

    # algorithms this backend implements (the planner picks among these)
    _PLANNABLE = (comp.ALG_FLAT, comp.ALG_RING, comp.ALG_TREE,
                  comp.ALG_HIERARCHICAL)

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM, compression=None):
        self.last_op_stats = None
        # host-side entry stamp BEFORE the program dispatch: a member
        # wedged inside the XLA collective (waiting on a peer) still shows
        # its last-entered (op, seq) in the flight recorder, which is what
        # the hang sweep compares across members
        seq = self._mark("allreduce", "enter")
        try:
            spec = comp.resolve_spec(compression)
            if spec is not None and op == ReduceOp.SUM and \
                    comp.is_float_dtype(getattr(tensor, "dtype", None)):
                # plan from metadata only — np.asarray would device_get the
                # tensor, and the plan usually says "stock" (small payloads,
                # compression='none'), where that copy is pure waste
                nbytes = int(getattr(tensor, "nbytes", 0) or 0)
                plan = topo_planner.plan_allreduce(
                    nbytes, self.topology(), spec, allowed=self._PLANNABLE)
                topo_planner.record_plan(plan.algorithm, plan.reason)
                if not plan.is_stock:
                    arr = np.asarray(tensor)
                    if plan.algorithm == comp.ALG_HIERARCHICAL:
                        return self._hierarchical_allreduce(arr, plan)
                    if plan.algorithm in (comp.ALG_RING, comp.ALG_TREE):
                        return self._decomposed_allreduce(arr, plan)
                    return self._quantized_allreduce(arr, plan)
            return self._reduce_impl(tensor, op)
        finally:
            self._mark("allreduce", "exit", seq=seq)

    def _decomposed_allreduce(self, arr, plan: comp.Plan):
        """Planner-built lossless variants: explicit ring (psum_scatter +
        all_gather) or recursive-halving-doubling tree instead of the
        stock fused psum — per-size schedule control the planner selects
        by link class and message size."""
        import jax

        # the ring/tree decompositions are LOSSLESS: keep the payload's own
        # float dtype (an f64 tensor must not round-trip through f32 on a
        # path the stock psum previously ran at full precision)
        n = arr.size
        flat = np.ascontiguousarray(arr).ravel()
        padded = comp.pad_to_multiple(flat, self._world_size)
        key = (plan.algorithm, padded.size, str(padded.dtype))
        fn = self._fn_cache.get(key)
        if fn is None:
            builder = (build_ring_allreduce
                       if plan.algorithm == comp.ALG_RING
                       else build_tree_allreduce)
            fn = builder(self._mesh, "world", self._world_size)
            self._fn_cache[key] = fn
        out = fn(self._global_stack(padded))
        result = np.asarray(jax.device_get(out))[:n]
        wire, inter = comp.estimate_wire_bytes(
            plan.algorithm, comp.SCHEME_NONE, int(padded.nbytes),
            self._world_size)
        self.last_op_stats = comp.OpStats(
            logical_bytes=int(arr.nbytes), wire_bytes=wire,
            algorithm=plan.algorithm, scheme=comp.SCHEME_NONE,
            inter_slice_bytes=inter)
        return result.reshape(arr.shape).astype(arr.dtype, copy=False)

    def _quantized_allreduce(self, arr, plan: comp.Plan):
        """EQuARX two-phase path: host codec quantizes the local payload
        (one authoritative codec for error feedback + stats), the jitted
        program moves int8 over the wire collectives."""
        import jax

        spec = plan.spec
        bs = spec.block_size
        n = arr.size
        codes, scales, _deq, qerr = comp.ef_quantize(
            self._group_name, "allreduce", arr, spec,
            pad_granule=self._world_size * bs)

        key = ("qallreduce", codes.size, bs, spec.accum_dtype)
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = build_quantized_allreduce(
                self._mesh, "world", self._world_size, bs, spec.accum_dtype)
            self._fn_cache[key] = fn
        out = fn(self._global_stack(codes), self._global_stack(scales))
        result = np.asarray(jax.device_get(out))[:n]
        wire = comp.wire_nbytes(codes, scales)
        self.last_op_stats = comp.OpStats(
            logical_bytes=int(arr.nbytes),
            # phase 1 all_to_all sends this rank's codes once; phase 2
            # allgather re-sends its 1/world requantized shard
            wire_bytes=wire + wire // max(self._world_size, 1),
            algorithm=comp.ALG_FLAT, scheme=plan.scheme, quant_error=qerr)
        return result.reshape(arr.shape).astype(arr.dtype, copy=False)

    _warned_hier_ef = False

    def _hierarchical_allreduce(self, arr, plan: comp.Plan):
        """Two-level ICI x DCN path over a (slice, intra) device mesh.

        The int8 DCN phase quantizes the intra-reduced shard DEVICE-side,
        so error feedback (a host-residual scheme) cannot apply here —
        warn once instead of silently honoring half the spec; quant_error
        is likewise unmeasured (sentinel -1 keeps the gauge honest)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = plan.spec
        if (spec.error_feedback and plan.scheme == comp.SCHEME_INT8
                and not XLAGroup._warned_hier_ef):
            XLAGroup._warned_hier_ef = True
            import logging

            logging.getLogger(__name__).warning(
                "error_feedback is not supported on the XLA hierarchical "
                "allreduce (device-side requantization); proceeding without "
                "residuals — use the flat int8 algorithm or the store "
                "backend if EF matters here")
        bs = spec.block_size
        ss = plan.slice_size
        nslices = self._world_size // ss
        n = arr.size
        flat = arr.ravel().astype(np.float32, copy=False)
        padded = comp.pad_to_multiple(flat, ss * bs)

        key = ("hallreduce", padded.size, nslices, ss, plan.scheme, bs,
               spec.accum_dtype)
        fn = self._fn_cache.get(key)
        mesh2 = self._fn_cache.get(("hmesh", nslices, ss))
        if mesh2 is None:
            mesh2 = jax.sharding.Mesh(
                np.array(self._devices).reshape(nslices, ss),
                ("slice", "intra"))
            self._fn_cache[("hmesh", nslices, ss)] = mesh2
        if fn is None:
            fn = build_hierarchical_allreduce(
                mesh2, nslices, ss, plan.scheme, bs, spec.accum_dtype)
            self._fn_cache[key] = fn
        sharding = NamedSharding(mesh2, P("slice", "intra"))
        local = jax.device_put(padded[None, None, ...], self._local_device)
        garr = jax.make_array_from_single_device_arrays(
            (nslices, ss, padded.size), sharding, [local])
        out = fn(garr)
        result = np.asarray(jax.device_get(out))[:n]
        wire, inter = comp.estimate_wire_bytes(
            comp.ALG_HIERARCHICAL, plan.scheme, int(padded.nbytes),
            self._world_size, ss, bs)
        self.last_op_stats = comp.OpStats(
            logical_bytes=int(arr.nbytes), wire_bytes=wire,
            algorithm=comp.ALG_HIERARCHICAL, scheme=plan.scheme,
            quant_error=-1.0 if plan.scheme == comp.SCHEME_INT8 else 0.0,
            inter_slice_bytes=inter)
        return result.reshape(arr.shape).astype(arr.dtype, copy=False)


    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self._reduce_impl(tensor, op)
        return out if self._rank == dst_rank else tensor

    def broadcast(self, tensor, src_rank: int = 0):
        import jax
        from jax.experimental import multihost_utils

        if self._world_size == 1:
            return tensor
        arr = np.asarray(tensor)
        seq = self._mark("broadcast", "enter")
        try:
            return np.asarray(
                multihost_utils.broadcast_one_to_all(
                    arr, is_source=self._rank == src_rank))
        finally:
            self._mark("broadcast", "exit", seq=seq)

    def allgather(self, tensor) -> List[Any]:
        import jax

        arr = np.asarray(tensor)
        garr = self._global_stack(arr)
        # all-gather = replicate the stacked array
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = jax.jit(
            lambda x: x, out_shardings=NamedSharding(self._mesh, P())
        )(garr)
        out = np.asarray(jax.device_get(rep))
        return [out[r] for r in range(self._world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr = np.asarray(tensor)
        if arr.shape[0] % self._world_size:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by {self._world_size}"
            )
        if op == ReduceOp.PRODUCT:
            shard = arr.shape[0] // self._world_size
            out = self._reduce_impl(tensor, op)
            return out[self._rank * shard:(self._rank + 1) * shard]
        garr = self._global_stack(arr)
        out = self._reducescatter_fn(_PSUM_OPS[op])(garr)
        return self._local_shard(out)

    def barrier(self):
        from jax.experimental import multihost_utils

        if self._world_size == 1:
            return
        seq = self._mark("barrier", "enter")
        try:
            multihost_utils.sync_global_devices(
                f"ray_tpu_collective_{self._group_name}")
        finally:
            self._mark("barrier", "exit", seq=seq)

    # -- p2p ----------------------------------------------------------------
    # Device path: when the group spans a real multi-process jax runtime,
    # send/recv pair up in a TWO-device mesh ppermute program — only the two
    # endpoint processes participate, and XLA routes the transfer over ICI
    # (reference analog: NCCL p2p in torch_tensor_accelerator_channel.py).
    # Shape/dtype ride the store so the receiver can allocate its input.
    # Host relay remains the fallback (single-process tests, mixed devices).

    def _device_p2p_ready(self) -> bool:
        import jax

        return self._world_size > 1 and jax.process_count() >= self._world_size

    def _pair_fn(self, src_rank: int, dst_rank: int, shape, dtype):
        key = ("p2p", src_rank, dst_rank, tuple(shape), str(dtype))
        fn = self._fn_cache.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            mesh = jax.sharding.Mesh(
                np.array([self._devices[src_rank], self._devices[dst_rank]]),
                ("pair",))

            def body(x):
                return jax.lax.ppermute(x, "pair", [(0, 1)])

            fn = jax.jit(
                _shard_map(body, mesh=mesh, in_specs=P("pair"), out_specs=P("pair"))
            )
            self._fn_cache[key] = fn
            self._fn_cache[("p2p_mesh", src_rank, dst_rank)] = mesh
        return fn, self._fn_cache[("p2p_mesh", src_rank, dst_rank)]

    def _pair_global(self, mesh, local_row, shape, dtype):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = jax.device_put(local_row[None, ...], self._local_device)
        return jax.make_array_from_single_device_arrays(
            (2, *shape), NamedSharding(mesh, P("pair")), [local])

    def _seq(self, attr: str, peer: int) -> int:
        table = getattr(self, attr, None)
        if table is None:
            table = {}
            setattr(self, attr, table)
        table[peer] = table.get(peer, 0) + 1
        return table[peer]

    def send(self, tensor, dst_rank: int):
        import ray_tpu

        arr = np.asarray(tensor)
        store = get_or_create_store()
        seq = self._seq("_send_seq", dst_rank)
        if self._device_p2p_ready():
            meta_key = (self._group_name, "xla_p2p_meta", self._rank, dst_rank, seq)
            ray_tpu.get(store.put.remote(meta_key, (arr.shape, arr.dtype.str)))
            fn, mesh = self._pair_fn(self._rank, dst_rank, arr.shape, arr.dtype)
            fn(self._pair_global(mesh, arr, arr.shape, arr.dtype))  # rendezvous
            return
        key = (self._group_name, "xla_p2p", self._rank, dst_rank, seq)
        ray_tpu.get(store.put.remote(key, arr))

    def recv(self, src_rank: int):
        store = get_or_create_store()
        seq = self._seq("_recv_seq", src_rank)
        if self._device_p2p_ready():
            meta_key = (self._group_name, "xla_p2p_meta", src_rank, self._rank, seq)
            shape, dtype_str = store_wait(store, "pop", (meta_key,))
            dtype = np.dtype(dtype_str)
            fn, mesh = self._pair_fn(src_rank, self._rank, shape, dtype)
            out = fn(self._pair_global(mesh, np.zeros(shape, dtype), shape, dtype))
            local = [sh for sh in out.addressable_shards
                     if sh.device == self._local_device]
            return np.asarray(local[0].data)[0] if local else np.asarray(out)[1]
        key = (self._group_name, "xla_p2p", src_rank, self._rank, seq)
        return store_wait(store, "pop", (key,))
