"""XLA backend: process-level collectives riding ICI/DCN via XLA.

The NCCL analog (reference: nccl_collective_group.py — cupy NCCL comms with
Rendezvous via a named store actor :30-82). TPU-native design: the store
actor publishes the jax.distributed coordinator address (instead of an
ncclUniqueId); every member calls jax.distributed.initialize; collective ops
are jitted shard_map programs over a one-axis mesh with ONE device per
member process, so XLA lowers them to ICI collectives inside a slice and
DCN collectives across slices.
"""

from __future__ import annotations

import functools
import socket
from typing import Any, List

import numpy as np

from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.store import get_or_create_store, store_wait
from ray_tpu.util.collective.types import ReduceOp

_PSUM_OPS = {
    ReduceOp.SUM: "psum",
    ReduceOp.MAX: "pmax",
    ReduceOp.MIN: "pmin",
}


from ray_tpu.util.jax_compat import shard_map as _shard_map  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _host_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class XLAGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        import jax

        self._ensure_process_group(world_size, rank, group_name)
        # One device per member process: the collective contract is
        # process-granular (each member contributes one tensor).
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) < world_size:
            if world_size == 1:
                by_proc = {0: jax.devices()[0]}
            else:
                raise RuntimeError(
                    f"xla group needs {world_size} jax processes, found {len(by_proc)}"
                )
        self._devices = [by_proc[p] for p in sorted(by_proc)[:world_size]]
        self._mesh = jax.sharding.Mesh(np.array(self._devices), ("world",))
        self._local_device = by_proc.get(jax.process_index(), self._devices[0])

    @staticmethod
    def _ensure_process_group(world_size: int, rank: int, group_name: str):
        """Rendezvous + jax.distributed.initialize (idempotent)."""
        import jax

        if world_size <= 1 or jax.process_count() >= world_size:
            return  # single process, or runtime already spans the group
        store = get_or_create_store()
        key = (group_name, "xla_coordinator")
        if rank == 0:
            import ray_tpu

            addr = f"{_host_ip()}:{_free_port()}"
            ray_tpu.get(store.put.remote(key, addr))
        else:
            addr = store_wait(store, "get", (key,))
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=world_size, process_id=rank
        )

    # -- jitted collective programs (cached per shape/dtype/op) -------------
    @functools.lru_cache(maxsize=None)
    def _allreduce_fn(self, op_name: str):
        import jax
        from jax.sharding import PartitionSpec as P

        def body(x):
            # x: [1, ...] local row of the stacked [world, ...] array
            return getattr(jax.lax, op_name)(x, "world")[0]

        return jax.jit(
            _shard_map(body, mesh=self._mesh, in_specs=P("world"), out_specs=P())
        )

    @functools.lru_cache(maxsize=None)
    def _reducescatter_fn(self, op_name: str):
        import jax
        from jax.sharding import PartitionSpec as P

        def body(x):
            # x: [1, ...] local row; output: this rank's reduced shard
            summed = getattr(jax.lax, op_name)(x, "world")[0]
            shard = summed.shape[0] // self._world_size
            idx = jax.lax.axis_index("world")
            return jax.lax.dynamic_slice_in_dim(summed, idx * shard, shard, axis=0)

        return jax.jit(
            _shard_map(body, mesh=self._mesh, in_specs=P("world"), out_specs=P("world"))
        )

    def _global_stack(self, arr):
        """Global [world, ...] array whose rank-th row is this process's arr."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = jax.device_put(arr[None, ...], self._local_device)
        sharding = NamedSharding(self._mesh, P("world"))
        return jax.make_array_from_single_device_arrays(
            (self._world_size, *arr.shape), sharding, [local]
        )

    def _local_shard(self, garr):
        """This process's shard of a 'world'-sharded global array."""
        shards = [s for s in garr.addressable_shards if s.device == self._local_device]
        return np.asarray(shards[0].data)

    # -- collectives --------------------------------------------------------
    def _reduce_impl(self, tensor, op: ReduceOp):
        import jax

        if op == ReduceOp.PRODUCT:
            # no pprod in lax; log-space or gather-reduce. Gather-reduce:
            rows = self.allgather(tensor)
            out = rows[0]
            for r in rows[1:]:
                out = out * r
            return out
        arr = np.asarray(tensor)
        garr = self._global_stack(arr)
        out = self._allreduce_fn(_PSUM_OPS[op])(garr)
        local = [s for s in out.addressable_shards if s.device == self._local_device]
        return np.asarray(local[0].data) if local else np.asarray(jax.device_get(out))

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._reduce_impl(tensor, op)

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self._reduce_impl(tensor, op)
        return out if self._rank == dst_rank else tensor

    def broadcast(self, tensor, src_rank: int = 0):
        import jax
        from jax.experimental import multihost_utils

        if self._world_size == 1:
            return tensor
        arr = np.asarray(tensor)
        return np.asarray(
            multihost_utils.broadcast_one_to_all(arr, is_source=self._rank == src_rank)
        )

    def allgather(self, tensor) -> List[Any]:
        import jax

        arr = np.asarray(tensor)
        garr = self._global_stack(arr)
        # all-gather = replicate the stacked array
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = jax.jit(
            lambda x: x, out_shardings=NamedSharding(self._mesh, P())
        )(garr)
        out = np.asarray(jax.device_get(rep))
        return [out[r] for r in range(self._world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr = np.asarray(tensor)
        if arr.shape[0] % self._world_size:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by {self._world_size}"
            )
        if op == ReduceOp.PRODUCT:
            shard = arr.shape[0] // self._world_size
            out = self._reduce_impl(tensor, op)
            return out[self._rank * shard:(self._rank + 1) * shard]
        garr = self._global_stack(arr)
        out = self._reducescatter_fn(_PSUM_OPS[op])(garr)
        return self._local_shard(out)

    def barrier(self):
        from jax.experimental import multihost_utils

        if self._world_size == 1:
            return
        multihost_utils.sync_global_devices(f"ray_tpu_collective_{self._group_name}")

    # -- p2p: store-relayed (host path). Device-to-device p2p inside one
    # program should use shard_map ppermute; cross-program p2p has no public
    # XLA API, so the host relay is the correct fallback. ------------------
    def send(self, tensor, dst_rank: int):
        import ray_tpu

        store = get_or_create_store()
        seq = getattr(self, "_send_seq", {}).get(dst_rank, 0) + 1
        if not hasattr(self, "_send_seq"):
            self._send_seq = {}
        self._send_seq[dst_rank] = seq
        key = (self._group_name, "xla_p2p", self._rank, dst_rank, seq)
        ray_tpu.get(store.put.remote(key, np.asarray(tensor)))

    def recv(self, src_rank: int):
        store = get_or_create_store()
        if not hasattr(self, "_recv_seq"):
            self._recv_seq = {}
        seq = self._recv_seq.get(src_rank, 0) + 1
        self._recv_seq[src_rank] = seq
        key = (self._group_name, "xla_p2p", src_rank, self._rank, seq)
        return store_wait(store, "pop", (key,))
