"""XLA backend: process-level collectives riding ICI/DCN via XLA.

The NCCL analog (reference: nccl_collective_group.py — cupy NCCL comms with
Rendezvous via a named store actor :30-82). TPU-native design: the store
actor publishes the jax.distributed coordinator address (instead of an
ncclUniqueId); every member calls jax.distributed.initialize; collective ops
are jitted shard_map programs over a one-axis mesh with ONE device per
member process, so XLA lowers them to ICI collectives inside a slice and
DCN collectives across slices.
"""

from __future__ import annotations

import socket
from typing import Any, List

import numpy as np

from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.store import get_or_create_store, store_wait
from ray_tpu.util.collective.types import ReduceOp

_PSUM_OPS = {
    ReduceOp.SUM: "psum",
    ReduceOp.MAX: "pmax",
    ReduceOp.MIN: "pmin",
}


from ray_tpu.util.jax_compat import shard_map as _shard_map  # noqa: E402


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _host_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class XLAGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        import jax

        self._ensure_process_group(world_size, rank, group_name)
        # One device per member process: the collective contract is
        # process-granular (each member contributes one tensor).
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        if len(by_proc) < world_size:
            if world_size == 1:
                by_proc = {0: jax.devices()[0]}
            else:
                raise RuntimeError(
                    f"xla group needs {world_size} jax processes, found {len(by_proc)}"
                )
        self._devices = [by_proc[p] for p in sorted(by_proc)[:world_size]]
        self._mesh = jax.sharding.Mesh(np.array(self._devices), ("world",))
        self._local_device = by_proc.get(jax.process_index(), self._devices[0])
        # per-instance program cache (NOT functools.lru_cache on methods —
        # that pins self and its Mesh forever, VERDICT r1 weak #4)
        self._fn_cache = {}

    @staticmethod
    def _ensure_process_group(world_size: int, rank: int, group_name: str):
        """Rendezvous + jax.distributed.initialize (idempotent)."""
        import jax

        if world_size <= 1 or jax.process_count() >= world_size:
            return  # single process, or runtime already spans the group
        store = get_or_create_store()
        key = (group_name, "xla_coordinator")
        if rank == 0:
            import ray_tpu

            addr = f"{_host_ip()}:{_free_port()}"
            ray_tpu.get(store.put.remote(key, addr))
        else:
            addr = store_wait(store, "get", (key,))
        jax.distributed.initialize(
            coordinator_address=addr, num_processes=world_size, process_id=rank
        )

    # -- jitted collective programs (cached per op in a per-instance dict) --
    def _allreduce_fn(self, op_name: str):
        fn = self._fn_cache.get(("allreduce", op_name))
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            def body(x):
                # x: [1, ...] local row of the stacked [world, ...] array
                return getattr(jax.lax, op_name)(x, "world")[0]

            fn = jax.jit(
                _shard_map(body, mesh=self._mesh, in_specs=P("world"), out_specs=P())
            )
            self._fn_cache[("allreduce", op_name)] = fn
        return fn

    def _reducescatter_fn(self, op_name: str):
        fn = self._fn_cache.get(("reducescatter", op_name))
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            def body(x):
                # x: [1, ...] local row; output: this rank's reduced shard
                summed = getattr(jax.lax, op_name)(x, "world")[0]
                shard = summed.shape[0] // self._world_size
                idx = jax.lax.axis_index("world")
                return jax.lax.dynamic_slice_in_dim(summed, idx * shard, shard, axis=0)

            fn = jax.jit(
                _shard_map(body, mesh=self._mesh, in_specs=P("world"), out_specs=P("world"))
            )
            self._fn_cache[("reducescatter", op_name)] = fn
        return fn

    def _global_stack(self, arr):
        """Global [world, ...] array whose rank-th row is this process's arr."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = jax.device_put(arr[None, ...], self._local_device)
        sharding = NamedSharding(self._mesh, P("world"))
        return jax.make_array_from_single_device_arrays(
            (self._world_size, *arr.shape), sharding, [local]
        )

    def _local_shard(self, garr):
        """This process's shard of a 'world'-sharded global array."""
        shards = [s for s in garr.addressable_shards if s.device == self._local_device]
        return np.asarray(shards[0].data)

    # -- collectives --------------------------------------------------------
    def _reduce_impl(self, tensor, op: ReduceOp):
        import jax

        if op == ReduceOp.PRODUCT:
            # no pprod in lax; log-space or gather-reduce. Gather-reduce:
            rows = self.allgather(tensor)
            out = rows[0]
            for r in rows[1:]:
                out = out * r
            return out
        arr = np.asarray(tensor)
        garr = self._global_stack(arr)
        out = self._allreduce_fn(_PSUM_OPS[op])(garr)
        local = [s for s in out.addressable_shards if s.device == self._local_device]
        return np.asarray(local[0].data) if local else np.asarray(jax.device_get(out))

    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        return self._reduce_impl(tensor, op)

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self._reduce_impl(tensor, op)
        return out if self._rank == dst_rank else tensor

    def broadcast(self, tensor, src_rank: int = 0):
        import jax
        from jax.experimental import multihost_utils

        if self._world_size == 1:
            return tensor
        arr = np.asarray(tensor)
        return np.asarray(
            multihost_utils.broadcast_one_to_all(arr, is_source=self._rank == src_rank)
        )

    def allgather(self, tensor) -> List[Any]:
        import jax

        arr = np.asarray(tensor)
        garr = self._global_stack(arr)
        # all-gather = replicate the stacked array
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = jax.jit(
            lambda x: x, out_shardings=NamedSharding(self._mesh, P())
        )(garr)
        out = np.asarray(jax.device_get(rep))
        return [out[r] for r in range(self._world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr = np.asarray(tensor)
        if arr.shape[0] % self._world_size:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by {self._world_size}"
            )
        if op == ReduceOp.PRODUCT:
            shard = arr.shape[0] // self._world_size
            out = self._reduce_impl(tensor, op)
            return out[self._rank * shard:(self._rank + 1) * shard]
        garr = self._global_stack(arr)
        out = self._reducescatter_fn(_PSUM_OPS[op])(garr)
        return self._local_shard(out)

    def barrier(self):
        from jax.experimental import multihost_utils

        if self._world_size == 1:
            return
        multihost_utils.sync_global_devices(f"ray_tpu_collective_{self._group_name}")

    # -- p2p ----------------------------------------------------------------
    # Device path: when the group spans a real multi-process jax runtime,
    # send/recv pair up in a TWO-device mesh ppermute program — only the two
    # endpoint processes participate, and XLA routes the transfer over ICI
    # (reference analog: NCCL p2p in torch_tensor_accelerator_channel.py).
    # Shape/dtype ride the store so the receiver can allocate its input.
    # Host relay remains the fallback (single-process tests, mixed devices).

    def _device_p2p_ready(self) -> bool:
        import jax

        return self._world_size > 1 and jax.process_count() >= self._world_size

    def _pair_fn(self, src_rank: int, dst_rank: int, shape, dtype):
        key = ("p2p", src_rank, dst_rank, tuple(shape), str(dtype))
        fn = self._fn_cache.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            mesh = jax.sharding.Mesh(
                np.array([self._devices[src_rank], self._devices[dst_rank]]),
                ("pair",))

            def body(x):
                return jax.lax.ppermute(x, "pair", [(0, 1)])

            fn = jax.jit(
                _shard_map(body, mesh=mesh, in_specs=P("pair"), out_specs=P("pair"))
            )
            self._fn_cache[key] = fn
            self._fn_cache[("p2p_mesh", src_rank, dst_rank)] = mesh
        return fn, self._fn_cache[("p2p_mesh", src_rank, dst_rank)]

    def _pair_global(self, mesh, local_row, shape, dtype):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = jax.device_put(local_row[None, ...], self._local_device)
        return jax.make_array_from_single_device_arrays(
            (2, *shape), NamedSharding(mesh, P("pair")), [local])

    def _seq(self, attr: str, peer: int) -> int:
        table = getattr(self, attr, None)
        if table is None:
            table = {}
            setattr(self, attr, table)
        table[peer] = table.get(peer, 0) + 1
        return table[peer]

    def send(self, tensor, dst_rank: int):
        import ray_tpu

        arr = np.asarray(tensor)
        store = get_or_create_store()
        seq = self._seq("_send_seq", dst_rank)
        if self._device_p2p_ready():
            meta_key = (self._group_name, "xla_p2p_meta", self._rank, dst_rank, seq)
            ray_tpu.get(store.put.remote(meta_key, (arr.shape, arr.dtype.str)))
            fn, mesh = self._pair_fn(self._rank, dst_rank, arr.shape, arr.dtype)
            fn(self._pair_global(mesh, arr, arr.shape, arr.dtype))  # rendezvous
            return
        key = (self._group_name, "xla_p2p", self._rank, dst_rank, seq)
        ray_tpu.get(store.put.remote(key, arr))

    def recv(self, src_rank: int):
        store = get_or_create_store()
        seq = self._seq("_recv_seq", src_rank)
        if self._device_p2p_ready():
            meta_key = (self._group_name, "xla_p2p_meta", src_rank, self._rank, seq)
            shape, dtype_str = store_wait(store, "pop", (meta_key,))
            dtype = np.dtype(dtype_str)
            fn, mesh = self._pair_fn(src_rank, self._rank, shape, dtype)
            out = fn(self._pair_global(mesh, np.zeros(shape, dtype), shape, dtype))
            local = [sh for sh in out.addressable_shards
                     if sh.device == self._local_device]
            return np.asarray(local[0].data)[0] if local else np.asarray(out)[1]
        key = (self._group_name, "xla_p2p", src_rank, self._rank, seq)
        return store_wait(store, "pop", (key,))
