"""STORE backend: collectives via the named store actor + object store.

The gloo analog (reference: gloo_collective_group.py:185): works between any
ray_tpu actors/tasks with no accelerator coupling — used for control-plane
collectives (ray.train.collective-style broadcast/barrier) and for tests.
Every op is a contribute/collect round on the store actor keyed by a
per-group monotonically increasing sequence number, so all ranks must issue
collectives in the same order (the standard collective contract).
"""

from __future__ import annotations

import time
from typing import Any, List

import numpy as np

from ray_tpu.util.collective import compression as comp
from ray_tpu.util.collective import planner as topo_planner
from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.store import (
    check_abort,
    get_or_create_store,
    store_wait,
)
from ray_tpu.util.collective.types import CollectiveAbortError, ReduceOp

_REDUCERS = {
    ReduceOp.SUM: lambda xs: _tree_reduce(np.add, xs),
    ReduceOp.PRODUCT: lambda xs: _tree_reduce(np.multiply, xs),
    ReduceOp.MIN: lambda xs: _tree_reduce(np.minimum, xs),
    ReduceOp.MAX: lambda xs: _tree_reduce(np.maximum, xs),
}


def _tree_reduce(op, xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = op(acc, x)
    return acc


def _to_numpy(tensor):
    """numpy view of a tensor + a converter back to the original kind."""
    if isinstance(tensor, np.ndarray):
        return tensor, lambda a: a
    mod = type(tensor).__module__
    if mod.startswith("jax") or "ArrayImpl" in type(tensor).__name__:
        import jax.numpy as jnp

        return np.asarray(tensor), lambda a: jnp.asarray(a)
    if mod.startswith("torch"):
        return tensor.detach().cpu().numpy(), None  # converter built lazily below
    return np.asarray(tensor), lambda a: a


def _convert_back(result_np, original):
    if isinstance(original, np.ndarray):
        return result_np
    mod = type(original).__module__
    if mod.startswith("jax") or "ArrayImpl" in type(original).__name__:
        import jax.numpy as jnp

        return jnp.asarray(result_np)
    if mod.startswith("torch"):
        import torch

        return torch.from_numpy(np.ascontiguousarray(result_np))
    if isinstance(original, (int, float)):
        return type(original)(result_np)
    return result_np


class StoreGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._store = get_or_create_store()
        self._seq = 0
        self._p2p_send_seq = {}
        self._p2p_recv_seq = {}
        # set to the abort reason once the group is poisoned; every
        # subsequent op raises immediately until the group is re-initialized
        self._aborted: str | None = None
        # register identity for the store's liveness monitor: a member
        # dying (or its node draining) aborts the whole group promptly
        self._join_membership()
        # join barrier so ops can't start before all ranks exist
        self._sync("join")
        # explicit topology for the planner, built AFTER the join barrier
        # (all members' node identities are registered by then) and cached
        # for the group's lifetime — membership change means re-init,
        # which builds a fresh group and re-derives/re-probes
        self._topology = self._build_topology()

    def _build_topology(self) -> topo_planner.Topology:
        """Topology from group-member node identity: ranks sharing a node
        form one latency domain (the "slice" of the store backend's
        hierarchical algorithm); the store round-trip probe calibrates
        the link β term.  Unknown node ids (driver-less tests) collapse
        to a single domain."""
        import ray_tpu

        ids = [None] * self._world_size
        try:
            members = ray_tpu.get(
                self._store.get_members.remote(self._group_name))
            for rank, m in (members or {}).items():
                if 0 <= rank < self._world_size:
                    ids[rank] = (m or {}).get("node_id")
        except Exception:  # noqa: BLE001 — topology is advisory
            pass
        slice_ids = tuple(i if i is not None else "?unknown" for i in ids)
        if all(i == "?unknown" for i in slice_ids):
            slice_ids = tuple([0] * self._world_size)
        kw = {}
        bw = self._probe_link_bandwidth()
        if bw is not None:
            kw["intra_bw"] = bw
            kw["inter_bw"] = bw
        return topo_planner.Topology.from_slice_ids(
            slice_ids, intra_link=topo_planner.LINK_HOST,
            inter_link=topo_planner.LINK_DCN, **kw)

    def _probe_link_bandwidth(self):
        """One-shot store-link probe (~1 ms): round-trip a 64 KiB payload
        through the store actor and derive bytes/s — every byte a store
        collective moves crosses this link, so it is the β term for every
        algorithm on this backend.  Failures fall back to defaults."""
        if self._world_size <= 1:
            return None
        try:
            import ray_tpu

            payload = np.ones(16384, np.float32)  # 64 KiB
            key = (self._group_name, "_bwprobe", self._rank)
            t0 = time.perf_counter()
            ray_tpu.get(self._store.put.remote(key, payload))
            ray_tpu.get(self._store.pop.remote(key))
            dt = time.perf_counter() - t0
            if dt <= 0:
                return None
            return 2 * payload.nbytes / dt
        except Exception:  # noqa: BLE001 — probe is advisory, never fatal
            return None

    def topology(self) -> topo_planner.Topology:
        return self._topology

    # algorithms this backend implements (no tree: pairwise exchange
    # rounds through a central store pay w·α per round, never winning)
    _PLANNABLE = (comp.ALG_FLAT, comp.ALG_RING, comp.ALG_HIERARCHICAL)

    def plan_explain(self, nbytes: int, compression=None) -> dict:
        """Debug surface: the planner's candidate table for a payload of
        ``nbytes`` on this group's real topology."""
        spec = comp.resolve_spec(compression)
        if spec is None:
            spec = self.default_compression
        return topo_planner.plan_explain(nbytes, self._topology, spec,
                                         allowed=self._PLANNABLE)

    def _join_membership(self):
        import ray_tpu

        member = {"actor_id": None, "node_id": None}
        try:
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker()
            if w.actor_id is not None:
                member["actor_id"] = w.actor_id.hex()
            if w.node_id is not None:
                member["node_id"] = w.node_id.hex()
        except Exception:  # noqa: BLE001 — driver-less test contexts
            pass
        ray_tpu.get(self._store.join_member.remote(
            self._group_name, self._rank, member))

    def _abort(self, reason: str):
        """Poison this group locally, book the abort metric, and raise."""
        if self._aborted is None:
            self._aborted = reason
            try:
                from ray_tpu._private import runtime_metrics

                runtime_metrics.inc_collective_abort("store", self._group_name)
            except Exception:  # noqa: BLE001 — abort metric is telemetry; the raise below is the point
                pass
        raise CollectiveAbortError(
            f"collective group {self._group_name!r} aborted: {reason}; "
            "re-init the group to continue")

    def _check_live(self):
        if self._aborted is not None:
            raise CollectiveAbortError(
                f"collective group {self._group_name!r} is poisoned "
                f"({self._aborted}); re-init the group to continue")

    def _guard(self, fn):
        """Run one store round; turn an abort sentinel/error into the
        poisoned state."""
        self._check_live()
        try:
            return fn()
        except CollectiveAbortError as e:
            self._abort(str(e))

    def _next_key(self, kind: str):
        self._seq += 1
        return (self._group_name, kind, self._seq)

    def _sync(self, kind: str):
        import ray_tpu

        def run():
            key = self._next_key(kind)
            self._mark(kind, "enter", seq=key[2])
            check_abort(ray_tpu.get(self._store.barrier_arrive.remote(
                key, self._rank, self._world_size)))
            store_wait(self._store, "barrier_done",
                       (key, self._rank, self._world_size))
            self._mark(kind, "exit", seq=key[2])

        self._guard(run)

    def _exchange(self, kind: str, value) -> dict:
        """All-to-all gather round: contribute own value, collect everyone's."""
        import ray_tpu

        def run():
            key = self._next_key(kind)
            self._mark(kind, "enter", seq=key[2])
            check_abort(ray_tpu.get(
                self._store.contribute.remote(key, self._rank, value)))
            out = store_wait(self._store, "collect",
                             (key, self._world_size, self._rank))
            self._mark(kind, "exit", seq=key[2])
            return out

        return self._guard(run)

    def _exchange_sub(self, kind: str, subrank: int, count: int, value,
                      member_ranks=None) -> dict:
        """Gather round inside a subgroup (hierarchical phases): the kind
        string embeds the subgroup id, so concurrent subgroups never share a
        key; every rank runs every phase exactly once, keeping the per-group
        sequence counter aligned across all ranks.  ``member_ranks`` names
        the subgroup's GROUP-GLOBAL ranks so the store's arrival monitor
        (hang diagnosis, straggler EWMAs) never sees subranks."""
        import ray_tpu

        def run():
            key = self._next_key(kind)
            self._mark(kind, "enter", seq=key[2])
            check_abort(ray_tpu.get(
                self._store.contribute.remote(key, subrank, value,
                                              self._rank, member_ranks)))
            out = store_wait(self._store, "collect", (key, count, subrank))
            self._mark(kind, "exit", seq=key[2])
            return out

        return self._guard(run)

    # -- collectives --------------------------------------------------------
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM, compression=None):
        self.last_op_stats = None
        arr, _ = _to_numpy(tensor)
        plan = self._plan(arr, op, compression)
        if plan.is_stock:
            by_rank = self._exchange("allreduce", arr)
            out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
            return _convert_back(out, tensor)
        if plan.algorithm == comp.ALG_HIERARCHICAL:
            out, stats = self._hierarchical_allreduce(arr, op, plan)
        elif plan.algorithm == comp.ALG_RING:
            out, stats = self._ring_allreduce(arr, op, plan)
        elif plan.scheme == comp.SCHEME_INT8:
            out, stats = self._quantized_allreduce(arr, plan)
        else:
            # a lossless algorithm this backend doesn't implement must
            # NEVER fall into the quantized path — run the stock exchange
            by_rank = self._exchange("allreduce", arr)
            out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
            return _convert_back(out, tensor)
        self.last_op_stats = stats
        return _convert_back(out.astype(arr.dtype, copy=False), tensor)

    def _plan(self, arr: np.ndarray, op: ReduceOp, compression) -> comp.Plan:
        spec = comp.resolve_spec(compression)
        plan = topo_planner.plan_allreduce(arr.nbytes, self._topology, spec,
                                           allowed=self._PLANNABLE)
        if spec is not None:
            topo_planner.record_plan(plan.algorithm, plan.reason)
        if plan.scheme != comp.SCHEME_NONE and (
                op != ReduceOp.SUM or not comp.is_float_dtype(arr.dtype)):
            # quantization is only meaningful for float SUM-reductions;
            # keep the (lossless) algorithm choice, drop the codec
            import dataclasses as _dc

            plan = _dc.replace(plan, scheme=comp.SCHEME_NONE)
        return plan

    def _ring_allreduce(self, arr: np.ndarray, op: ReduceOp,
                        plan: comp.Plan):
        """Chunked ring (reduce-scatter + allgather through the store):
        the payload splits into ``world`` chunks; every rank contributes
        ALL chunks up front (uploads pipeline instead of serializing in
        one giant round trip), but each chunk's reduction is owned by one
        rank, which alone downloads that chunk's ``world`` contributions
        — per-rank download drops from (n-1)·S (flat exchange) to ~2·S.
        The reduced chunks then allgather in one ordinary round."""
        import ray_tpu

        w = self._world_size

        def run():
            flat = comp.pad_to_multiple(arr.ravel(), w)
            cs = flat.size // w
            # every rank derives the SAME key sequence (loop order is part
            # of the collective contract, like any op ordering)
            rs_keys = [self._next_key(f"ring_rs_c{j}") for j in range(w)]
            ag_key = self._next_key("ring_ag")
            self._mark("ring_allreduce", "enter", seq=ag_key[2])
            # phase 1a — contribute all chunks WITHOUT waiting: uploads
            # overlap each other and the collect below
            refs = [self._store.contribute.remote(
                rs_keys[j], self._rank, flat[j * cs:(j + 1) * cs])
                for j in range(w)]
            # phase 1b — reduce the one chunk this rank owns (single
            # reader: the store GCs the entry on our read)
            for v in ray_tpu.get(refs):
                check_abort(v)
            by_rank = store_wait(
                self._store, "collect", (rs_keys[self._rank], w, self._rank, 1))
            mine = _REDUCERS[op]([by_rank[r] for r in range(w)])
            # phase 2 — allgather the reduced chunks
            check_abort(ray_tpu.get(self._store.contribute.remote(
                ag_key, self._rank, mine)))
            by_owner = store_wait(self._store, "collect",
                                  (ag_key, w, self._rank))
            out = np.concatenate(
                [by_owner[r] for r in range(w)])[:arr.size]
            self._mark("ring_allreduce", "exit", seq=ag_key[2])
            wire, _ = comp.estimate_wire_bytes(
                comp.ALG_RING, comp.SCHEME_NONE, int(flat.nbytes), w)
            stats = comp.OpStats(
                logical_bytes=int(arr.nbytes), wire_bytes=wire,
                algorithm=comp.ALG_RING, scheme=comp.SCHEME_NONE)
            return out.reshape(arr.shape), stats

        return self._guard(run)

    def _quantized_allreduce(self, arr: np.ndarray, plan: comp.Plan):
        """Flat quantized: every rank contributes int8 codes + per-block
        scales instead of the raw float payload; each rank dequantizes all
        contributions and sums — all ranks see bit-identical results."""
        spec = plan.spec
        n = arr.size
        codes, scales, _deq, qerr = comp.ef_quantize(
            self._group_name, "allreduce", arr, spec)
        by_rank = self._exchange("allreduce_q", (codes, scales))
        acc = np.zeros(n, np.float32)
        for r in range(self._world_size):
            c_r, s_r = by_rank[r]
            acc += comp.dequantize_blocks(c_r, s_r, n, spec.block_size)
        stats = comp.OpStats(
            logical_bytes=int(arr.nbytes),
            wire_bytes=comp.wire_nbytes(codes, scales),
            algorithm=comp.ALG_FLAT, scheme=plan.scheme, quant_error=qerr)
        return acc.reshape(arr.shape), stats

    def _hierarchical_allreduce(self, arr: np.ndarray, op: ReduceOp,
                                plan: comp.Plan):
        """Two-level algorithm (TACCL-shaped): intra-slice reduce-scatter,
        inter-slice exchange on 1/slice shards (optionally quantized — this
        is the DCN phase the algorithm exists to shrink), intra-slice
        allgather.  Slices are contiguous rank blocks of ``slice_size``."""
        spec = plan.spec
        ss = plan.slice_size
        nslices = self._world_size // ss
        sid, idx = self._rank // ss, self._rank % ss
        # global-rank membership of each subgroup this rank exchanges in —
        # the arrival monitor is keyed by global rank, never subrank
        slice_ranks = [sid * ss + j for j in range(ss)]
        cross_ranks = [s * ss + idx for s in range(nslices)]
        flat = comp.pad_to_multiple(arr.ravel(), ss)
        shard_n = flat.size // ss
        lo, hi = idx * shard_n, (idx + 1) * shard_n

        # phase 1 — intra-slice reduce-scatter: exchange full payloads
        # inside the slice, each member reduces its own shard
        by_idx = self._exchange_sub(f"hier_rs_s{sid}", idx, ss, flat,
                                    member_ranks=slice_ranks)
        my_shard = _REDUCERS[op]([by_idx[j][lo:hi] for j in range(ss)])
        wire_intra = int(flat.nbytes)

        # phase 2 — inter-slice allreduce of the shard among same-index
        # members across slices (1/slice of the payload crosses DCN)
        quantized = plan.scheme == comp.SCHEME_INT8
        if quantized:
            codes, scales, _deq, qerr = comp.ef_quantize(
                self._group_name, "allreduce_hier", my_shard, spec)
            by_slice = self._exchange_sub(
                f"hier_x_i{idx}", sid, nslices, (codes, scales),
                member_ranks=cross_ranks)
            acc = np.zeros(shard_n, np.float32)
            for s in range(nslices):
                c_s, s_s = by_slice[s]
                acc += comp.dequantize_blocks(c_s, s_s, shard_n,
                                              spec.block_size)
            global_shard = acc.astype(flat.dtype, copy=False)
            wire_inter = comp.wire_nbytes(codes, scales)
        else:
            qerr = 0.0
            by_slice = self._exchange_sub(
                f"hier_x_i{idx}", sid, nslices, my_shard,
                member_ranks=cross_ranks)
            global_shard = _REDUCERS[op](
                [by_slice[s] for s in range(nslices)])
            wire_inter = int(my_shard.nbytes)

        # phase 3 — intra-slice allgather of the globally-reduced shards
        by_idx3 = self._exchange_sub(f"hier_ag_s{sid}", idx, ss, global_shard,
                                     member_ranks=slice_ranks)
        out = np.concatenate([by_idx3[j] for j in range(ss)])[:arr.size]
        wire_intra += int(global_shard.nbytes)

        stats = comp.OpStats(
            logical_bytes=int(arr.nbytes),
            wire_bytes=wire_intra + wire_inter,
            algorithm=comp.ALG_HIERARCHICAL, scheme=plan.scheme,
            quant_error=qerr, inter_slice_bytes=wire_inter)
        return out.reshape(arr.shape), stats

    def allreduce_bucketed(self, arrays: List[np.ndarray],
                           op: ReduceOp = ReduceOp.SUM, compression=None):
        """Pipelined bucketed allreduce (the DDP overlap trick on the
        store transport): ``arrays`` is the deterministic bucket sequence
        (identical on every rank — the bucket partition is a pure function
        of the gradient tree); bucket k+1's contribution is ISSUED while
        bucket k's round is still uploading/collecting, so store round
        trips overlap instead of serializing end-to-end.

        Per-bucket compression composes with PR 3's codec: the
        error-feedback residual keys embed the bucket index (op string
        ``allreduce_b<k>``), so each bucket carries its own residual.
        Returns the reduced arrays in bucket order; ``last_op_stats``
        aggregates the whole sequence.
        """
        import ray_tpu

        self.last_op_stats = None
        w = self._world_size
        spec = comp.resolve_spec(compression)
        if spec is None:
            spec = self.default_compression

        def run():
            staged = []  # (key, ref, quantized, qmeta...)
            logical = wire = 0
            qerr = 0.0
            for k, arr in enumerate(arrays):
                a = np.ascontiguousarray(arr)
                quantize = (spec is not None
                            and spec.scheme == comp.SCHEME_INT8
                            and op == ReduceOp.SUM
                            and comp.is_float_dtype(a.dtype)
                            and a.nbytes >= spec.min_bytes and w > 1)
                if spec is not None:
                    topo_planner.record_plan(
                        comp.ALG_FLAT,
                        "bucketed_pipeline" if w > 1 else "solo")
                if quantize:
                    codes, scales, _deq, e = comp.ef_quantize(
                        self._group_name, f"allreduce_b{k}", a, spec)
                    payload = (codes, scales)
                    wire += comp.wire_nbytes(codes, scales)
                    qerr = max(qerr, e)
                else:
                    payload = a
                    wire += int(a.nbytes)
                logical += int(a.nbytes)
                key = self._next_key(f"bucket_ar_b{k}")
                self._mark("bucket_allreduce", "enter", seq=key[2])
                # fire-and-continue: the next bucket's upload overlaps
                # this round's completion
                ref = self._store.contribute.remote(key, self._rank, payload)
                staged.append((key, ref, quantize, a))
            outs = []
            for key, ref, quantize, a in staged:
                check_abort(ray_tpu.get(ref))
                by_rank = store_wait(self._store, "collect",
                                     (key, w, self._rank))
                if quantize:
                    acc = np.zeros(a.size, np.float32)
                    for r in range(w):
                        c_r, s_r = by_rank[r]
                        acc += comp.dequantize_blocks(
                            c_r, s_r, a.size, spec.block_size)
                    out = acc.reshape(a.shape).astype(a.dtype, copy=False)
                else:
                    out = _REDUCERS[op]([by_rank[r] for r in range(w)])
                self._mark("bucket_allreduce", "exit", seq=key[2])
                outs.append(out)
            if spec is not None:
                self.last_op_stats = comp.OpStats(
                    logical_bytes=logical, wire_bytes=wire,
                    algorithm=comp.ALG_FLAT,
                    scheme=(comp.SCHEME_INT8 if any(s[2] for s in staged)
                            else comp.SCHEME_NONE),
                    quant_error=qerr)
            return outs

        return self._guard(run)

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("reduce", arr)
        if self._rank != dst_rank:
            return tensor
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        return _convert_back(out, tensor)

    def broadcast(self, tensor, src_rank: int = 0):
        arr, _ = _to_numpy(tensor) if tensor is not None else (None, None)
        by_rank = self._exchange("broadcast", arr if self._rank == src_rank else None)
        return _convert_back(by_rank[src_rank], tensor) if tensor is not None \
            else by_rank[src_rank]

    def allgather(self, tensor) -> List[Any]:
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("allgather", arr)
        return [_convert_back(by_rank[r], tensor) for r in range(self._world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        if arr.shape[0] % self._world_size:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by world size "
                f"{self._world_size}"
            )
        by_rank = self._exchange("reducescatter", arr)
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        shard = out.shape[0] // self._world_size
        return _convert_back(out[self._rank * shard:(self._rank + 1) * shard], tensor)

    def barrier(self):
        self._sync("barrier")

    # -- p2p ----------------------------------------------------------------
    def send(self, tensor, dst_rank: int):
        import ray_tpu

        arr, _ = _to_numpy(tensor)

        def run():
            seq = self._p2p_send_seq.get(dst_rank, 0) + 1
            self._p2p_send_seq[dst_rank] = seq
            key = (self._group_name, "p2p", self._rank, dst_rank, seq)
            check_abort(ray_tpu.get(self._store.put.remote(key, arr)))

        self._guard(run)

    def recv(self, src_rank: int):
        def run():
            seq = self._p2p_recv_seq.get(src_rank, 0) + 1
            self._p2p_recv_seq[src_rank] = seq
            key = (self._group_name, "p2p", src_rank, self._rank, seq)
            return store_wait(self._store, "pop", (key,))

        return self._guard(run)

    def destroy(self):
        import ray_tpu

        try:
            ray_tpu.get(self._store.leave_group.remote(
                self._group_name, self._rank))
        except Exception:  # noqa: BLE001 — store may already be gone
            pass
        super().destroy()
