"""STORE backend: collectives via the named store actor + object store.

The gloo analog (reference: gloo_collective_group.py:185): works between any
ray_tpu actors/tasks with no accelerator coupling — used for control-plane
collectives (ray.train.collective-style broadcast/barrier) and for tests.
Every op is a contribute/collect round on the store actor keyed by a
per-group monotonically increasing sequence number, so all ranks must issue
collectives in the same order (the standard collective contract).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ray_tpu.util.collective import compression as comp
from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.store import (
    check_abort,
    get_or_create_store,
    store_wait,
)
from ray_tpu.util.collective.types import CollectiveAbortError, ReduceOp

_REDUCERS = {
    ReduceOp.SUM: lambda xs: _tree_reduce(np.add, xs),
    ReduceOp.PRODUCT: lambda xs: _tree_reduce(np.multiply, xs),
    ReduceOp.MIN: lambda xs: _tree_reduce(np.minimum, xs),
    ReduceOp.MAX: lambda xs: _tree_reduce(np.maximum, xs),
}


def _tree_reduce(op, xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = op(acc, x)
    return acc


def _to_numpy(tensor):
    """numpy view of a tensor + a converter back to the original kind."""
    if isinstance(tensor, np.ndarray):
        return tensor, lambda a: a
    mod = type(tensor).__module__
    if mod.startswith("jax") or "ArrayImpl" in type(tensor).__name__:
        import jax.numpy as jnp

        return np.asarray(tensor), lambda a: jnp.asarray(a)
    if mod.startswith("torch"):
        return tensor.detach().cpu().numpy(), None  # converter built lazily below
    return np.asarray(tensor), lambda a: a


def _convert_back(result_np, original):
    if isinstance(original, np.ndarray):
        return result_np
    mod = type(original).__module__
    if mod.startswith("jax") or "ArrayImpl" in type(original).__name__:
        import jax.numpy as jnp

        return jnp.asarray(result_np)
    if mod.startswith("torch"):
        import torch

        return torch.from_numpy(np.ascontiguousarray(result_np))
    if isinstance(original, (int, float)):
        return type(original)(result_np)
    return result_np


class StoreGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._store = get_or_create_store()
        self._seq = 0
        self._p2p_send_seq = {}
        self._p2p_recv_seq = {}
        # set to the abort reason once the group is poisoned; every
        # subsequent op raises immediately until the group is re-initialized
        self._aborted: str | None = None
        # register identity for the store's liveness monitor: a member
        # dying (or its node draining) aborts the whole group promptly
        self._join_membership()
        # join barrier so ops can't start before all ranks exist
        self._sync("join")

    def _join_membership(self):
        import ray_tpu

        member = {"actor_id": None, "node_id": None}
        try:
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker()
            if w.actor_id is not None:
                member["actor_id"] = w.actor_id.hex()
            if w.node_id is not None:
                member["node_id"] = w.node_id.hex()
        except Exception:  # noqa: BLE001 — driver-less test contexts
            pass
        ray_tpu.get(self._store.join_member.remote(
            self._group_name, self._rank, member))

    def _abort(self, reason: str):
        """Poison this group locally, book the abort metric, and raise."""
        if self._aborted is None:
            self._aborted = reason
            try:
                from ray_tpu._private import runtime_metrics

                runtime_metrics.inc_collective_abort("store", self._group_name)
            except Exception:  # noqa: BLE001
                pass
        raise CollectiveAbortError(
            f"collective group {self._group_name!r} aborted: {reason}; "
            "re-init the group to continue")

    def _check_live(self):
        if self._aborted is not None:
            raise CollectiveAbortError(
                f"collective group {self._group_name!r} is poisoned "
                f"({self._aborted}); re-init the group to continue")

    def _guard(self, fn):
        """Run one store round; turn an abort sentinel/error into the
        poisoned state."""
        self._check_live()
        try:
            return fn()
        except CollectiveAbortError as e:
            self._abort(str(e))

    def _next_key(self, kind: str):
        self._seq += 1
        return (self._group_name, kind, self._seq)

    def _sync(self, kind: str):
        import ray_tpu

        def run():
            key = self._next_key(kind)
            self._mark(kind, "enter", seq=key[2])
            check_abort(ray_tpu.get(self._store.barrier_arrive.remote(
                key, self._rank, self._world_size)))
            store_wait(self._store, "barrier_done",
                       (key, self._rank, self._world_size))
            self._mark(kind, "exit", seq=key[2])

        self._guard(run)

    def _exchange(self, kind: str, value) -> dict:
        """All-to-all gather round: contribute own value, collect everyone's."""
        import ray_tpu

        def run():
            key = self._next_key(kind)
            self._mark(kind, "enter", seq=key[2])
            check_abort(ray_tpu.get(
                self._store.contribute.remote(key, self._rank, value)))
            out = store_wait(self._store, "collect",
                             (key, self._world_size, self._rank))
            self._mark(kind, "exit", seq=key[2])
            return out

        return self._guard(run)

    def _exchange_sub(self, kind: str, subrank: int, count: int, value,
                      member_ranks=None) -> dict:
        """Gather round inside a subgroup (hierarchical phases): the kind
        string embeds the subgroup id, so concurrent subgroups never share a
        key; every rank runs every phase exactly once, keeping the per-group
        sequence counter aligned across all ranks.  ``member_ranks`` names
        the subgroup's GROUP-GLOBAL ranks so the store's arrival monitor
        (hang diagnosis, straggler EWMAs) never sees subranks."""
        import ray_tpu

        def run():
            key = self._next_key(kind)
            self._mark(kind, "enter", seq=key[2])
            check_abort(ray_tpu.get(
                self._store.contribute.remote(key, subrank, value,
                                              self._rank, member_ranks)))
            out = store_wait(self._store, "collect", (key, count, subrank))
            self._mark(kind, "exit", seq=key[2])
            return out

        return self._guard(run)

    # -- collectives --------------------------------------------------------
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM, compression=None):
        self.last_op_stats = None
        arr, _ = _to_numpy(tensor)
        plan = self._plan(arr, op, compression)
        if plan.is_stock:
            by_rank = self._exchange("allreduce", arr)
            out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
            return _convert_back(out, tensor)
        if plan.algorithm == comp.ALG_HIERARCHICAL:
            out, stats = self._hierarchical_allreduce(arr, op, plan)
        else:
            out, stats = self._quantized_allreduce(arr, plan)
        self.last_op_stats = stats
        return _convert_back(out.astype(arr.dtype, copy=False), tensor)

    def _plan(self, arr: np.ndarray, op: ReduceOp, compression) -> comp.Plan:
        spec = comp.resolve_spec(compression)
        plan = comp.choose_plan(arr.nbytes, self._world_size, spec,
                                num_slices=self._topology_num_slices())
        if plan.scheme != comp.SCHEME_NONE and (
                op != ReduceOp.SUM or not comp.is_float_dtype(arr.dtype)):
            # quantization is only meaningful for float SUM-reductions;
            # keep the (lossless) algorithm choice, drop the codec
            import dataclasses as _dc

            plan = _dc.replace(plan, scheme=comp.SCHEME_NONE)
        return plan

    def _quantized_allreduce(self, arr: np.ndarray, plan: comp.Plan):
        """Flat quantized: every rank contributes int8 codes + per-block
        scales instead of the raw float payload; each rank dequantizes all
        contributions and sums — all ranks see bit-identical results."""
        spec = plan.spec
        n = arr.size
        codes, scales, _deq, qerr = comp.ef_quantize(
            self._group_name, "allreduce", arr, spec)
        by_rank = self._exchange("allreduce_q", (codes, scales))
        acc = np.zeros(n, np.float32)
        for r in range(self._world_size):
            c_r, s_r = by_rank[r]
            acc += comp.dequantize_blocks(c_r, s_r, n, spec.block_size)
        stats = comp.OpStats(
            logical_bytes=int(arr.nbytes),
            wire_bytes=comp.wire_nbytes(codes, scales),
            algorithm=comp.ALG_FLAT, scheme=plan.scheme, quant_error=qerr)
        return acc.reshape(arr.shape), stats

    def _hierarchical_allreduce(self, arr: np.ndarray, op: ReduceOp,
                                plan: comp.Plan):
        """Two-level algorithm (TACCL-shaped): intra-slice reduce-scatter,
        inter-slice exchange on 1/slice shards (optionally quantized — this
        is the DCN phase the algorithm exists to shrink), intra-slice
        allgather.  Slices are contiguous rank blocks of ``slice_size``."""
        spec = plan.spec
        ss = plan.slice_size
        nslices = self._world_size // ss
        sid, idx = self._rank // ss, self._rank % ss
        # global-rank membership of each subgroup this rank exchanges in —
        # the arrival monitor is keyed by global rank, never subrank
        slice_ranks = [sid * ss + j for j in range(ss)]
        cross_ranks = [s * ss + idx for s in range(nslices)]
        flat = comp.pad_to_multiple(arr.ravel(), ss)
        shard_n = flat.size // ss
        lo, hi = idx * shard_n, (idx + 1) * shard_n

        # phase 1 — intra-slice reduce-scatter: exchange full payloads
        # inside the slice, each member reduces its own shard
        by_idx = self._exchange_sub(f"hier_rs_s{sid}", idx, ss, flat,
                                    member_ranks=slice_ranks)
        my_shard = _REDUCERS[op]([by_idx[j][lo:hi] for j in range(ss)])
        wire_intra = int(flat.nbytes)

        # phase 2 — inter-slice allreduce of the shard among same-index
        # members across slices (1/slice of the payload crosses DCN)
        quantized = plan.scheme == comp.SCHEME_INT8
        if quantized:
            codes, scales, _deq, qerr = comp.ef_quantize(
                self._group_name, "allreduce_hier", my_shard, spec)
            by_slice = self._exchange_sub(
                f"hier_x_i{idx}", sid, nslices, (codes, scales),
                member_ranks=cross_ranks)
            acc = np.zeros(shard_n, np.float32)
            for s in range(nslices):
                c_s, s_s = by_slice[s]
                acc += comp.dequantize_blocks(c_s, s_s, shard_n,
                                              spec.block_size)
            global_shard = acc.astype(flat.dtype, copy=False)
            wire_inter = comp.wire_nbytes(codes, scales)
        else:
            qerr = 0.0
            by_slice = self._exchange_sub(
                f"hier_x_i{idx}", sid, nslices, my_shard,
                member_ranks=cross_ranks)
            global_shard = _REDUCERS[op](
                [by_slice[s] for s in range(nslices)])
            wire_inter = int(my_shard.nbytes)

        # phase 3 — intra-slice allgather of the globally-reduced shards
        by_idx3 = self._exchange_sub(f"hier_ag_s{sid}", idx, ss, global_shard,
                                     member_ranks=slice_ranks)
        out = np.concatenate([by_idx3[j] for j in range(ss)])[:arr.size]
        wire_intra += int(global_shard.nbytes)

        stats = comp.OpStats(
            logical_bytes=int(arr.nbytes),
            wire_bytes=wire_intra + wire_inter,
            algorithm=comp.ALG_HIERARCHICAL, scheme=plan.scheme,
            quant_error=qerr, inter_slice_bytes=wire_inter)
        return out.reshape(arr.shape), stats

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("reduce", arr)
        if self._rank != dst_rank:
            return tensor
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        return _convert_back(out, tensor)

    def broadcast(self, tensor, src_rank: int = 0):
        arr, _ = _to_numpy(tensor) if tensor is not None else (None, None)
        by_rank = self._exchange("broadcast", arr if self._rank == src_rank else None)
        return _convert_back(by_rank[src_rank], tensor) if tensor is not None \
            else by_rank[src_rank]

    def allgather(self, tensor) -> List[Any]:
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("allgather", arr)
        return [_convert_back(by_rank[r], tensor) for r in range(self._world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        if arr.shape[0] % self._world_size:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by world size "
                f"{self._world_size}"
            )
        by_rank = self._exchange("reducescatter", arr)
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        shard = out.shape[0] // self._world_size
        return _convert_back(out[self._rank * shard:(self._rank + 1) * shard], tensor)

    def barrier(self):
        self._sync("barrier")

    # -- p2p ----------------------------------------------------------------
    def send(self, tensor, dst_rank: int):
        import ray_tpu

        arr, _ = _to_numpy(tensor)

        def run():
            seq = self._p2p_send_seq.get(dst_rank, 0) + 1
            self._p2p_send_seq[dst_rank] = seq
            key = (self._group_name, "p2p", self._rank, dst_rank, seq)
            check_abort(ray_tpu.get(self._store.put.remote(key, arr)))

        self._guard(run)

    def recv(self, src_rank: int):
        def run():
            seq = self._p2p_recv_seq.get(src_rank, 0) + 1
            self._p2p_recv_seq[src_rank] = seq
            key = (self._group_name, "p2p", src_rank, self._rank, seq)
            return store_wait(self._store, "pop", (key,))

        return self._guard(run)

    def destroy(self):
        import ray_tpu

        try:
            ray_tpu.get(self._store.leave_group.remote(
                self._group_name, self._rank))
        except Exception:  # noqa: BLE001 — store may already be gone
            pass
        super().destroy()
