"""STORE backend: collectives via the named store actor + object store.

The gloo analog (reference: gloo_collective_group.py:185): works between any
ray_tpu actors/tasks with no accelerator coupling — used for control-plane
collectives (ray.train.collective-style broadcast/barrier) and for tests.
Every op is a contribute/collect round on the store actor keyed by a
per-group monotonically increasing sequence number, so all ranks must issue
collectives in the same order (the standard collective contract).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from ray_tpu.util.collective.collective_group.base_group import BaseGroup
from ray_tpu.util.collective.store import get_or_create_store, store_wait
from ray_tpu.util.collective.types import ReduceOp

_REDUCERS = {
    ReduceOp.SUM: lambda xs: _tree_reduce(np.add, xs),
    ReduceOp.PRODUCT: lambda xs: _tree_reduce(np.multiply, xs),
    ReduceOp.MIN: lambda xs: _tree_reduce(np.minimum, xs),
    ReduceOp.MAX: lambda xs: _tree_reduce(np.maximum, xs),
}


def _tree_reduce(op, xs):
    acc = xs[0]
    for x in xs[1:]:
        acc = op(acc, x)
    return acc


def _to_numpy(tensor):
    """numpy view of a tensor + a converter back to the original kind."""
    if isinstance(tensor, np.ndarray):
        return tensor, lambda a: a
    mod = type(tensor).__module__
    if mod.startswith("jax") or "ArrayImpl" in type(tensor).__name__:
        import jax.numpy as jnp

        return np.asarray(tensor), lambda a: jnp.asarray(a)
    if mod.startswith("torch"):
        return tensor.detach().cpu().numpy(), None  # converter built lazily below
    return np.asarray(tensor), lambda a: a


def _convert_back(result_np, original):
    if isinstance(original, np.ndarray):
        return result_np
    mod = type(original).__module__
    if mod.startswith("jax") or "ArrayImpl" in type(original).__name__:
        import jax.numpy as jnp

        return jnp.asarray(result_np)
    if mod.startswith("torch"):
        import torch

        return torch.from_numpy(np.ascontiguousarray(result_np))
    if isinstance(original, (int, float)):
        return type(original)(result_np)
    return result_np


class StoreGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._store = get_or_create_store()
        self._seq = 0
        self._p2p_send_seq = {}
        self._p2p_recv_seq = {}
        # join barrier so ops can't start before all ranks exist
        self._sync("join")

    def _next_key(self, kind: str):
        self._seq += 1
        return (self._group_name, kind, self._seq)

    def _sync(self, kind: str):
        import ray_tpu

        key = self._next_key(kind)
        ray_tpu.get(self._store.barrier_arrive.remote(key, self._rank, self._world_size))
        store_wait(self._store, "barrier_done", (key, self._rank, self._world_size))

    def _exchange(self, kind: str, value) -> dict:
        """All-to-all gather round: contribute own value, collect everyone's."""
        import ray_tpu

        key = self._next_key(kind)
        ray_tpu.get(self._store.contribute.remote(key, self._rank, value))
        return store_wait(self._store, "collect", (key, self._world_size, self._rank))

    # -- collectives --------------------------------------------------------
    def allreduce(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("allreduce", arr)
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        return _convert_back(out, tensor)

    def reduce(self, tensor, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("reduce", arr)
        if self._rank != dst_rank:
            return tensor
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        return _convert_back(out, tensor)

    def broadcast(self, tensor, src_rank: int = 0):
        arr, _ = _to_numpy(tensor) if tensor is not None else (None, None)
        by_rank = self._exchange("broadcast", arr if self._rank == src_rank else None)
        return _convert_back(by_rank[src_rank], tensor) if tensor is not None \
            else by_rank[src_rank]

    def allgather(self, tensor) -> List[Any]:
        arr, _ = _to_numpy(tensor)
        by_rank = self._exchange("allgather", arr)
        return [_convert_back(by_rank[r], tensor) for r in range(self._world_size)]

    def reducescatter(self, tensor, op: ReduceOp = ReduceOp.SUM):
        arr, _ = _to_numpy(tensor)
        if arr.shape[0] % self._world_size:
            raise ValueError(
                f"reducescatter dim0 {arr.shape[0]} not divisible by world size "
                f"{self._world_size}"
            )
        by_rank = self._exchange("reducescatter", arr)
        out = _REDUCERS[op]([by_rank[r] for r in range(self._world_size)])
        shard = out.shape[0] // self._world_size
        return _convert_back(out[self._rank * shard:(self._rank + 1) * shard], tensor)

    def barrier(self):
        self._sync("barrier")

    # -- p2p ----------------------------------------------------------------
    def send(self, tensor, dst_rank: int):
        import ray_tpu

        arr, _ = _to_numpy(tensor)
        seq = self._p2p_send_seq.get(dst_rank, 0) + 1
        self._p2p_send_seq[dst_rank] = seq
        key = (self._group_name, "p2p", self._rank, dst_rank, seq)
        ray_tpu.get(self._store.put.remote(key, arr))

    def recv(self, src_rank: int):
        seq = self._p2p_recv_seq.get(src_rank, 0) + 1
        self._p2p_recv_seq[src_rank] = seq
        key = (self._group_name, "p2p", src_rank, self._rank, seq)
        return store_wait(self._store, "pop", (key,))
