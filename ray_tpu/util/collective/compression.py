"""Compression-aware collectives: block-quantized codec + algorithm policy.

Two papers drive this layer (PAPERS.md):

- EQuARX (arxiv 2506.17615): block-wise int8 quantization with per-block
  scales INSIDE a two-phase allreduce (quantize -> reduce-scatter with
  wide accumulation -> requantize -> allgather -> dequantize) recovers
  1.5-2x effective bandwidth with negligible quality loss.
- TACCL (arxiv 2111.04867): the algorithm should follow topology and
  message size — small messages stay flat/uncompressed (latency-bound),
  large multislice messages go hierarchical (intra-slice reduce-scatter,
  inter-slice exchange on 1/slice shards, intra-slice allgather).

This module is the shared substrate: the numpy codec (store backend, device
channels, error-feedback bookkeeping), the jax codec (XLA collective
programs, gradient compression inside jitted train steps), the
``CompressionSpec`` users hand to ``collective.allreduce(compression=)`` /
``init_collective_group(compression=)`` / ``make_train_step(
grad_compression=)``, and the size/topology selection policy.

Quantization is LOSSY: it is safe for SUM-reductions of gradients and
other noise-tolerant aggregates (optionally with error feedback, which
carries each round's quantization error into the next round), and wrong
for exact-value traffic (ids, bitmasks, losses you assert on).  The
policy never compresses unless a spec asks for it, and ``ReduceOp``s
other than SUM always fall back to the uncompressed path.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

SCHEME_NONE = "none"
SCHEME_INT8 = "int8"
_SCHEMES = (SCHEME_NONE, SCHEME_INT8)

ALG_FLAT = "flat"
ALG_HIERARCHICAL = "hierarchical"
ALG_RING = "ring"            # bandwidth-optimal reduce-scatter + allgather
ALG_TREE = "tree"            # recursive halving-doubling (pow2 worlds)

DEFAULT_BLOCK_SIZE = 256
# below this the op is latency-bound: int8 would save microseconds of wire
# at the cost of a quantize/dequantize pass and quality — stay flat bf16
DEFAULT_MIN_BYTES = 64 * 1024


@dataclasses.dataclass(frozen=True)
class CompressionSpec:
    """User-facing knob set.

    scheme:         "int8" (block-quantized) or "none" (algorithm-only —
                    e.g. hierarchical routing without quantization).
    block_size:     elements per scale block (EQuARX-style).
    min_bytes:      messages smaller than this stay flat/uncompressed.
    error_feedback: fold this round's quantization error into the next
                    round's input (per group/op/shape residual state).
    hierarchical:   True/False force; None = auto (used when the topology
                    reports >1 slice, or when ``slice_size`` is given).
    slice_size:     members per slice for the hierarchical algorithm
                    (None = infer from topology / don't go hierarchical).
    accum_dtype:    reduction accumulator dtype for the quantized XLA
                    two-phase program ("bfloat16" per EQuARX; "float32"
                    when quality headroom matters more than speed).
    """

    scheme: str = SCHEME_INT8
    block_size: int = DEFAULT_BLOCK_SIZE
    min_bytes: int = DEFAULT_MIN_BYTES
    error_feedback: bool = False
    hierarchical: Optional[bool] = None
    slice_size: Optional[int] = None
    accum_dtype: str = "bfloat16"

    def __post_init__(self):
        if self.scheme not in _SCHEMES:
            raise ValueError(
                f"unknown compression scheme {self.scheme!r}; one of {_SCHEMES}")
        if self.block_size <= 0:
            raise ValueError(f"block_size must be positive, got {self.block_size}")
        if self.slice_size is not None and self.slice_size <= 0:
            raise ValueError(f"slice_size must be positive, got {self.slice_size}")


def resolve_spec(compression) -> Optional[CompressionSpec]:
    """Canonicalize the ``compression=`` argument.

    None -> None (disabled / inherit the group default upstream);
    "none" -> a spec that forces the stock path; "int8" -> defaults;
    dict -> CompressionSpec(**dict); CompressionSpec -> itself.
    """
    if compression is None:
        return None
    if isinstance(compression, CompressionSpec):
        return compression
    if isinstance(compression, str):
        if compression == SCHEME_NONE:
            return CompressionSpec(scheme=SCHEME_NONE, hierarchical=False)
        if compression == SCHEME_INT8:
            return CompressionSpec()
        raise ValueError(
            f"unknown compression {compression!r}; use 'int8', 'none', "
            "a dict of CompressionSpec fields, or a CompressionSpec")
    if isinstance(compression, dict):
        return CompressionSpec(**compression)
    raise TypeError(f"cannot interpret compression={compression!r}")


@dataclasses.dataclass(frozen=True)
class Plan:
    """The planner's verdict for ONE collective call."""

    algorithm: str                       # flat | ring | tree | hierarchical
    scheme: str                          # none | int8
    slice_size: int = 1                  # members per slice when hierarchical
    spec: Optional[CompressionSpec] = None
    reason: str = ""                     # why the planner picked this

    @property
    def is_stock(self) -> bool:
        """True when the op should take the exact pre-compression code path."""
        return self.algorithm == ALG_FLAT and self.scheme == SCHEME_NONE


_STOCK_PLAN = Plan(ALG_FLAT, SCHEME_NONE)


def choose_plan(nbytes: int, world_size: int,
                spec: Optional[CompressionSpec], *,
                num_slices: int = 1, topology=None) -> Plan:
    """Message-size + topology selection, delegated to the planner
    (``util/collective/planner.py`` — TACCL-flavored α-β cost model over
    an explicit topology descriptor).

    - no spec, or payload under ``min_bytes``: flat + uncompressed (the
      stock path, byte-identical to compression-off).
    - hierarchical when the spec forces a valid slice_size, or when auto
      and the topology's domains form aligned contiguous blocks; a
      multi-domain topology whose domains CANNOT be slice-aligned refuses
      the hierarchy (reason ``unaligned_slices``) instead of guessing.
    - ring / tree for large lossless payloads per the link-class cost
      model; quantization per the spec's scheme (large SUM payloads only;
      the op check lives in the backend, which falls back for non-SUM).

    ``topology`` is the explicit descriptor backends build from device /
    node metadata; ``num_slices`` remains as the metadata-only fallback
    (contiguous equal slices assumed — exactly what it meant before).
    """
    from ray_tpu.util.collective import planner as _planner

    if topology is None:
        if num_slices > 1 and world_size % num_slices == 0:
            ss = world_size // num_slices
            topology = _planner.Topology.from_slice_ids(
                tuple(r // ss for r in range(world_size)))
        elif num_slices > 1:
            # uneven domain report with no real descriptor: refuse the
            # hierarchy downstream rather than invent a slice boundary
            topology = _planner.Topology.from_slice_ids(
                tuple(min(r, num_slices - 1) for r in range(world_size)))
        else:
            topology = _planner.Topology.flat(world_size)
    return _planner.plan_allreduce(nbytes, topology, spec)


# ---------------------------------------------------------------------------
# numpy codec (store backend, device channels, error-feedback residuals)
# ---------------------------------------------------------------------------


def is_float_dtype(dtype) -> bool:
    """Float check that also recognizes the ml_dtypes extension floats
    (bfloat16, float8_*) numpy reports as kind 'V' — bf16 gradients are
    the codec's primary customer — and foreign dtype objects like
    torch.float32.  None (no dtype metadata, e.g. a plain list) is NOT
    float: np.dtype(None) would default to float64 and lossily quantize
    values the caller never put in an array."""
    if dtype is None:
        return False
    try:
        dt = np.dtype(dtype)
    except TypeError:
        return "float" in str(dtype)  # torch.float32, tf.float32, ...
    if np.issubdtype(dt, np.floating):
        return True
    return "float" in dt.name  # bfloat16, float8_e4m3fn, ... via ml_dtypes


def estimate_wire_bytes(algorithm: str, scheme: str, logical_bytes: int,
                        world_size: int, slice_size: int = 1,
                        block_size: int = DEFAULT_BLOCK_SIZE
                        ) -> Tuple[int, int]:
    """(total_wire, inter_slice) per-rank byte model for an f32 payload —
    the ONE formula the benchmarks and the XLA backend's OpStats share, so
    bench rows and recorded metrics can't drift apart.  int8 payload =
    codes (1 byte/elem) + scales (4 bytes per block); the flat two-phase
    algorithm re-sends its 1/world requantized shard in the allgather;
    hierarchical = full payload intra (reduce-scatter) + globally-reduced
    shard intra (allgather) + the 1/slice shard across the DCN boundary.
    Ignores the codec's tail-padding (exact figures come from
    wire_nbytes on the real arrays where available)."""
    def int8_bytes(nbytes: int) -> int:
        return nbytes // 4 + nbytes // block_size

    if algorithm == ALG_HIERARCHICAL:
        shard = logical_bytes // max(slice_size, 1)
        inter = int8_bytes(shard) if scheme == SCHEME_INT8 else shard
        return logical_bytes + shard + inter, inter
    if algorithm in (ALG_RING, ALG_TREE):
        # reduce-scatter + allgather decompositions (explicit ring, or
        # recursive halving-doubling): each rank moves (n-1)/n·S per
        # phase, twice — lossless, so the scheme never changes the volume
        w = max(world_size, 1)
        return 2 * (w - 1) * logical_bytes // w, 0
    if scheme == SCHEME_INT8:
        one = int8_bytes(logical_bytes)
        return one + one // max(world_size, 1), 0
    return logical_bytes, 0


def dtype_from_name(name: str) -> np.dtype:
    """Inverse of ``np.dtype(...).name`` that also resolves the ml_dtypes
    extension floats (plain ``np.dtype('bfloat16')`` raises unless the
    name is registered)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pad_to_multiple(flat: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad a 1-D array up to a length multiple (codec/shard granule)."""
    rem = flat.size % multiple
    if rem == 0:
        return flat
    return np.concatenate([flat, np.zeros(multiple - rem, dtype=flat.dtype)])


def quantize_blocks(arr: np.ndarray,
                    block_size: int = DEFAULT_BLOCK_SIZE
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Block-wise symmetric int8: returns (codes [ceil(n/bs)*bs] int8,
    scales [nblocks] float32).  Zero blocks quantize to zero codes with a
    zero scale, so dequantization is exact there."""
    flat = np.ascontiguousarray(arr).ravel().astype(np.float32, copy=False)
    padded = pad_to_multiple(flat, block_size)
    blocks = padded.reshape(-1, block_size)
    maxabs = np.max(np.abs(blocks), axis=1)
    scales = (maxabs / 127.0).astype(np.float32)
    safe = np.where(scales > 0.0, scales, 1.0).astype(np.float32)
    codes = np.clip(np.rint(blocks / safe[:, None]), -127, 127).astype(np.int8)
    return codes.reshape(-1), scales


def dequantize_blocks(codes: np.ndarray, scales: np.ndarray, n: int,
                      block_size: int = DEFAULT_BLOCK_SIZE,
                      dtype=np.float32) -> np.ndarray:
    """Inverse of :func:`quantize_blocks`; returns the first ``n`` elements."""
    blocks = codes.reshape(-1, block_size).astype(np.float32) * \
        scales[:, None].astype(np.float32)
    return blocks.reshape(-1)[:n].astype(dtype, copy=False)


def wire_nbytes(codes: np.ndarray, scales: np.ndarray) -> int:
    """Bytes this quantized payload puts on the wire (codes + scales)."""
    return int(codes.nbytes + scales.nbytes)


def relative_error(original: np.ndarray, reconstructed: np.ndarray) -> float:
    """||x - x'|| / ||x|| (0 for an all-zero input) — the per-op quality
    figure recorded into the quant-error gauge."""
    x = np.asarray(original, dtype=np.float32).ravel()
    r = np.asarray(reconstructed, dtype=np.float32).ravel()
    norm = float(np.linalg.norm(x))
    if norm == 0.0:
        return 0.0
    return float(np.linalg.norm(x - r) / norm)


# ---------------------------------------------------------------------------
# Error feedback: per (group, op, shape, dtype) residual carried between
# rounds.  r_{t} = e_t - deQ(Q(e_t)) where e_t = x_t + r_{t-1}; the SGD-
# with-EF literature (and EQuARX's appendix) shows the accumulated error
# re-enters the average instead of being lost.
# ---------------------------------------------------------------------------


class ErrorFeedbackStore:
    """Process-local residual registry keyed per group/op/shape/dtype."""

    def __init__(self):
        self._residuals: Dict[Tuple, np.ndarray] = {}
        self._lock = threading.Lock()

    @staticmethod
    def key(group_name: str, op: str, arr: np.ndarray) -> Tuple:
        return (group_name, op, tuple(arr.shape), str(arr.dtype))

    def fold(self, key: Tuple, flat: np.ndarray) -> np.ndarray:
        """input + carried residual (float32)."""
        with self._lock:
            r = self._residuals.get(key)
        e = flat.astype(np.float32, copy=True)
        if r is not None and r.shape == e.shape:
            e += r
        return e

    def update(self, key: Tuple, folded: np.ndarray, dequantized: np.ndarray):
        with self._lock:
            self._residuals[key] = (folded - dequantized).astype(np.float32)

    def get(self, key: Tuple) -> Optional[np.ndarray]:
        with self._lock:
            return self._residuals.get(key)

    def clear_group(self, group_name: str):
        with self._lock:
            for k in [k for k in self._residuals if k[0] == group_name]:
                del self._residuals[k]


error_feedback = ErrorFeedbackStore()


def ef_quantize(group_name: str, op: str, arr: np.ndarray,
                spec: CompressionSpec, pad_granule: Optional[int] = None):
    """The one fold-residual → quantize → dequantize → update-residual
    sequence every backend's quantized path runs (flat store, hierarchical
    store DCN phase, flat XLA): returns ``(codes, scales, deq, qerr)``
    where ``deq`` is the local round trip over the first ``arr.size``
    elements and ``qerr`` its relative L2 error.  ``pad_granule`` pads the
    folded payload before encoding (the XLA two-phase program needs rows
    divisible by world*block)."""
    flat = np.ascontiguousarray(arr).ravel()
    key = None
    if spec.error_feedback:
        key = error_feedback.key(group_name, op, arr)
        folded = error_feedback.fold(key, flat)
    else:
        folded = flat.astype(np.float32, copy=False)
    payload = pad_to_multiple(folded, pad_granule) if pad_granule else folded
    codes, scales = quantize_blocks(payload, spec.block_size)
    deq = dequantize_blocks(codes, scales, flat.size, spec.block_size)
    if key is not None:
        error_feedback.update(key, folded, deq)
    return codes, scales, deq, relative_error(folded, deq)


# ---------------------------------------------------------------------------
# Per-op stats: the backend fills one of these for every allreduce so the
# API layer can record logical vs wire bytes, quant error, and the chosen
# algorithm into metrics/spans without re-deriving the plan.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OpStats:
    logical_bytes: int = 0          # payload at the API boundary
    wire_bytes: int = 0             # what actually crossed the transport
    algorithm: str = ALG_FLAT
    scheme: str = SCHEME_NONE
    quant_error: float = 0.0        # relative L2 of the local round trip
    inter_slice_bytes: int = 0      # DCN-phase share of wire_bytes (hier.)


# ---------------------------------------------------------------------------
# jax codec (device-side requantization inside XLA collective programs and
# gradient compression inside jitted train steps).  Mirrors the numpy codec
# bit-for-bit up to float32 rounding of the scales.
# ---------------------------------------------------------------------------


def jnp_quantize_blocks(x, block_size: int = DEFAULT_BLOCK_SIZE):
    """jax version of :func:`quantize_blocks`; ``x`` is flat with
    ``x.size % block_size == 0`` (pad at trace time)."""
    import jax.numpy as jnp

    blocks = x.reshape(-1, block_size).astype(jnp.float32)
    maxabs = jnp.max(jnp.abs(blocks), axis=1)
    scales = maxabs / 127.0
    safe = jnp.where(scales > 0.0, scales, 1.0)
    codes = jnp.clip(jnp.round(blocks / safe[:, None]), -127, 127)
    return codes.astype(jnp.int8).reshape(-1), scales


def jnp_dequantize_blocks(codes, scales, block_size: int = DEFAULT_BLOCK_SIZE,
                          dtype=None):
    import jax.numpy as jnp

    blocks = codes.reshape(-1, block_size).astype(jnp.float32) * \
        scales[:, None].astype(jnp.float32)
    out = blocks.reshape(-1)
    return out.astype(dtype) if dtype is not None else out


# ---------------------------------------------------------------------------
# Gradient compression as an optax transform: chain BEFORE the optimizer in
# make_train_step(grad_compression=...).  The quantize->dequantize round
# trip runs inside the jitted SPMD step, modeling the compressed gradient
# sync; with error_feedback the residual tree persists in the optimizer
# state (structurally params-like, so it inherits the params' shardings).
# ---------------------------------------------------------------------------


def compress_gradients(compression="int8"):
    """optax.GradientTransformation applying the block codec to gradients.

    Leaves smaller than ``min_bytes`` pass through untouched (the same
    size policy the collective layer applies); non-float leaves pass
    through always.
    """
    import jax
    import jax.numpy as jnp
    import optax

    spec = resolve_spec(compression)
    if spec is None or spec.scheme == SCHEME_NONE:
        return optax.identity()
    bs = spec.block_size

    def _eligible(g) -> bool:
        return (hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)
                and g.size * g.dtype.itemsize >= spec.min_bytes)

    def _roundtrip(flat):
        padded = jnp.pad(flat, (0, (-flat.size) % bs))
        codes, scales = jnp_quantize_blocks(padded, bs)
        return jnp_dequantize_blocks(codes, scales, bs)[:flat.size]

    if not spec.error_feedback:
        def update_fn(updates, state, params=None):
            del params

            def leaf(g):
                if not _eligible(g):
                    return g
                flat = g.reshape(-1)
                return _roundtrip(flat).astype(g.dtype).reshape(g.shape)

            return jax.tree.map(leaf, updates), state

        return optax.GradientTransformation(
            lambda params: optax.EmptyState(), update_fn)

    from typing import NamedTuple

    class _State(NamedTuple):
        residual: Any  # same structure as params -> inherits param shardings

    def init_fn(params):
        return _State(residual=jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params))

    def update_fn(updates, state, params=None):
        del params

        # two independent maps (XLA CSEs the duplicated quantize under
        # jit) rather than one map returning (update, residual) pairs —
        # unzipping pair-tuples with is_leaf=isinstance(...,tuple) would
        # misfire on pytrees that themselves contain tuple/NamedTuple
        # nodes, silently dropping fields
        def new_update(g, r):
            if not _eligible(g):
                return g
            flat = g.reshape(-1).astype(jnp.float32) + r.reshape(-1)
            return _roundtrip(flat).astype(g.dtype).reshape(g.shape)

        def new_resid(g, r):
            if not _eligible(g):
                return r
            flat = g.reshape(-1).astype(jnp.float32) + r.reshape(-1)
            return (flat - _roundtrip(flat)).reshape(g.shape)

        return (jax.tree.map(new_update, updates, state.residual),
                _State(residual=jax.tree.map(new_resid, updates,
                                             state.residual)))

    return optax.GradientTransformation(init_fn, update_fn)
