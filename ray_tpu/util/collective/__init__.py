"""ray_tpu.util.collective — collectives across actors/tasks.

reference: python/ray/util/collective/ (API collective.py:150-652). Backends:
``xla`` (jax.distributed + XLA collectives over ICI/DCN — the NCCL analog)
and ``store`` (named-store-actor data plane — the gloo analog).
"""

from ray_tpu.util.collective.collective import (
    allgather,
    allreduce,
    allreduce_pytree,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    plan_explain,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.util.collective.types import (
    Backend,
    CollectiveAbortError,
    ReduceOp,
)

__all__ = [
    "CollectiveAbortError",
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "is_group_initialized",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "allreduce_pytree",
    "plan_explain",
    "reduce",
    "broadcast",
    "allgather",
    "reducescatter",
    "barrier",
    "send",
    "recv",
    "Backend",
    "ReduceOp",
]
