"""User-defined application metrics: Counter / Gauge / Histogram / Sketch.

TPU-native rebuild of the reference's metrics API
(reference: python/ray/util/metrics.py; C++ registry src/ray/stats/metric.h:109,
exposition pipeline _private/metrics_agent.py:29,57,346).

Metrics are recorded into a process-local registry; each worker/driver
periodically (and on flush) pushes snapshots to the GCS, which aggregates the
latest value per (metric, tag-set, reporter).  ``prometheus_text()`` renders
the cluster-wide aggregate in Prometheus exposition format — what the
reference's per-node MetricsAgent serves to Prometheus.

``Sketch`` (beyond the reference) is a DDSketch-style quantile sketch
(_private/latency_sketch.py): log-bucketed, constant memory, bounded
relative error at ANY quantile, and — unlike Histogram — LOSSLESSLY
mergeable across reporters, so p99s computed from the GCS aggregate equal
the p99 of the combined stream.  Sketch points ride the same throttled
ReportMetrics push; Prometheus rendering is the summary convention
(``name{quantile="0.99"}``).
"""

from __future__ import annotations

import bisect
import os
import socket as _socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.analysis.lock_witness import make_lock

_REGISTRY_LOCK = make_lock("metrics._REGISTRY_LOCK")
_REGISTRY: Dict[str, "Metric"] = {}

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
]


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base class; subclasses implement the record semantics."""

    _kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        # Re-declaring a metric (e.g. inside a task that runs repeatedly on
        # the same worker) adopts the existing state instead of resetting it.
        with _REGISTRY_LOCK:
            prior = _REGISTRY.get(name)
            if prior is not None and prior._kind == self._kind:
                self._lock = prior._lock
                self._points = prior._points
            else:
                self._lock = make_lock("Metric._lock")
                self._points: Dict[Tuple[Tuple[str, str], ...], float] = {}
            _REGISTRY[name] = self

    @property
    def info(self) -> Dict[str, object]:
        return {
            "name": self._name,
            "description": self._description,
            "tag_keys": self._tag_keys,
            "default_tags": dict(self._default_tags),
        }

    def set_default_tags(self, default_tags: Dict[str, str]):
        self._check_tags(default_tags)
        self._default_tags = dict(default_tags)
        return self

    def _check_tags(self, tags: Optional[Dict[str, str]]):
        for k in tags or ():
            if k not in self._tag_keys:
                raise ValueError(
                    f"tag {k!r} not declared in tag_keys={self._tag_keys} of metric {self._name!r}"
                )

    def _merged(self, tags: Optional[Dict[str, str]]):
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return _tag_key(merged)

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"name": self._name, "kind": self._kind, "tags": dict(k), "value": v,
                 "description": self._description}
                for k, v in self._points.items()
            ]


class Counter(Metric):
    """Monotonically increasing value (reference: util/metrics.py Counter)."""

    _kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc() requires value > 0")
        self._check_tags(tags)
        key = self._merged(tags)
        with self._lock:
            self._points[key] = self._points.get(key, 0.0) + value

    def with_tags(self, tags: Optional[Dict[str, str]] = None) -> "BoundCounter":
        self._check_tags(tags)
        return BoundCounter(self, self._merged(tags))


class Gauge(Metric):
    """Last-value-wins metric (reference: util/metrics.py Gauge)."""

    _kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._check_tags(tags)
        with self._lock:
            self._points[self._merged(tags)] = float(value)

    def with_tags(self, tags: Optional[Dict[str, str]] = None) -> "BoundGauge":
        self._check_tags(tags)
        return BoundGauge(self, self._merged(tags))


class Histogram(Metric):
    """Distribution metric with static bucket boundaries."""

    _kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        # _hist/boundaries must exist before super().__init__ publishes the
        # instance into the registry (a concurrent collect_local() snapshots).
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        with _REGISTRY_LOCK:
            prior = _REGISTRY.get(name)
        if isinstance(prior, Histogram) and prior.boundaries == self.boundaries:
            self._hist = prior._hist
        else:
            # per-tagset: (bucket counts, sum, count)
            self._hist: Dict[Tuple, List] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._check_tags(tags)
        key = self._merged(tags)
        with self._lock:
            st = self._hist.get(key)
            if st is None:
                st = self._hist[key] = [[0] * (len(self.boundaries) + 1), 0.0, 0]
            buckets, _, _ = st
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            st[1] += value
            st[2] += 1

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                {"name": self._name, "kind": "histogram", "tags": dict(k),
                 "boundaries": list(self.boundaries), "buckets": list(st[0]),
                 "sum": st[1], "count": st[2], "description": self._description}
                for k, st in self._hist.items()
            ]

    def with_tags(self, tags: Optional[Dict[str, str]] = None) -> "BoundHistogram":
        self._check_tags(tags)
        return BoundHistogram(self, self._merged(tags))


class Sketch(Metric):
    """Mergeable quantile sketch metric (DDSketch-style; see
    _private/latency_sketch.py).  Use for latency distributions whose TAIL
    must stay accurate after folding across replicas/nodes — serve TTFT and
    inter-token latency are the canonical users."""

    _kind = "sketch"

    def __init__(self, name: str, description: str = "",
                 relative_accuracy: float = 0.01,
                 tag_keys: Optional[Sequence[str]] = None):
        self.relative_accuracy = float(relative_accuracy)
        with _REGISTRY_LOCK:
            prior = _REGISTRY.get(name)
        if (isinstance(prior, Sketch)
                and prior.relative_accuracy == self.relative_accuracy):
            self._sketches = prior._sketches
        else:
            # per-tagset LatencySketch
            self._sketches: Dict[Tuple, object] = {}
        super().__init__(name, description, tag_keys)

    def _sketch_for(self, key):
        st = self._sketches.get(key)
        if st is None:
            from ray_tpu._private.latency_sketch import LatencySketch

            st = self._sketches[key] = LatencySketch(self.relative_accuracy)
        return st

    def observe(self, value: float, n: int = 1,
                tags: Optional[Dict[str, str]] = None):
        self._check_tags(tags)
        key = self._merged(tags)
        with self._lock:
            self._sketch_for(key).add(value, n)

    def _snapshot(self) -> List[dict]:
        with self._lock:
            return [
                dict({"name": self._name, "kind": "sketch", "tags": dict(k),
                      "description": self._description}, **st.to_point())
                for k, st in self._sketches.items()
            ]

    def with_tags(self, tags: Optional[Dict[str, str]] = None) -> "BoundSketch":
        self._check_tags(tags)
        return BoundSketch(self, self._merged(tags))


# ---------------------------------------------------------------------------
# Bound recorders — the constant-cost hot path for built-in runtime metrics
# (reference: the C++ stats fast path, src/ray/stats/metric.h Record()).
# The tag-set is resolved ONCE at bind time; each record is a registry
# check, a lock, and one dict/list update, so instrumenting a dispatch loop
# costs O(100ns)/point.  The registry check (one unlocked dict read) keeps
# a long-lived recorder valid across ANY re-declaration of its metric —
# including a Histogram re-declared with different boundaries, which swaps
# in a fresh state dict the old instance no longer feeds.
# ---------------------------------------------------------------------------


class BoundCounter:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: Counter, key):
        self._m, self._key = metric, key

    def inc(self, value: float = 1.0):
        m = self._m
        cur = _REGISTRY.get(m._name)
        if cur is not m and type(cur) is type(m):
            self._m = m = cur
        with m._lock:
            m._points[self._key] = m._points.get(self._key, 0.0) + value


class BoundGauge:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: Gauge, key):
        self._m, self._key = metric, key

    def set(self, value: float):
        m = self._m
        cur = _REGISTRY.get(m._name)
        if cur is not m and type(cur) is type(m):
            self._m = m = cur
        with m._lock:
            m._points[self._key] = float(value)


class BoundHistogram:
    __slots__ = ("_m", "_key", "_bounds")

    def __init__(self, metric: Histogram, key):
        self._m, self._key = metric, key
        self._bounds = metric.boundaries

    def observe(self, value: float):
        m = self._m
        cur = _REGISTRY.get(m._name)
        if cur is not m and type(cur) is type(m):
            self._m = m = cur
            self._bounds = cur.boundaries
        i = bisect.bisect_left(self._bounds, value)
        with m._lock:
            st = m._hist.get(self._key)
            if st is None:
                st = m._hist[self._key] = [[0] * (len(self._bounds) + 1), 0.0, 0]
            st[0][i] += 1
            st[1] += value
            st[2] += 1


class BoundSketch:
    __slots__ = ("_m", "_key")

    def __init__(self, metric: Sketch, key):
        self._m, self._key = metric, key

    def observe(self, value: float, n: int = 1):
        m = self._m
        cur = _REGISTRY.get(m._name)
        if cur is not m and type(cur) is type(m):
            self._m = m = cur
        with m._lock:
            st = m._sketches.get(self._key)
            if st is None:
                from ray_tpu._private.latency_sketch import LatencySketch

                st = m._sketches[self._key] = LatencySketch(
                    m.relative_accuracy)
            st.add(value, n)


def collect_local() -> List[dict]:
    """Snapshot every metric registered in this process."""
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    out: List[dict] = []
    for m in metrics:
        out.extend(m._snapshot())
    return out


_REPORTER_ID: Optional[str] = None
# GCS channel for processes that host runtime components but no CoreWorker
# (a head-node raylet/GCS process): anything with .call(method, payload,
# timeout=). First registration wins; a worker, when present, is preferred.
_FALLBACK_GCS = None
_PUSH_LOCK = make_lock("metrics._PUSH_LOCK")
_LAST_PUSH = 0.0


def reporter_id() -> str:
    """Stable per-PROCESS reporter identity.  Every pusher in one process
    (driver worker, in-process raylets, in-process GCS) reports under the
    SAME name, so the GCS stores one latest full-registry snapshot per
    process and counters are never double-aggregated."""
    global _REPORTER_ID
    if _REPORTER_ID is None:
        _REPORTER_ID = f"{_socket.gethostname()}:{os.getpid()}"
    return _REPORTER_ID


def set_fallback_gcs(client) -> None:
    """Register a GCS channel for metric pushes from worker-less processes.
    No-op if one is already registered."""
    global _FALLBACK_GCS
    if _FALLBACK_GCS is None:
        _FALLBACK_GCS = client


def _gcs_channel():
    from ray_tpu._private.worker import get_global_worker

    w = get_global_worker()
    if w is not None:
        return w.gcs
    return _FALLBACK_GCS


def push_to_gcs(timeout: float = 10, **call_kwargs):
    """Push this process's metric snapshot to the GCS aggregate.
    ``call_kwargs`` pass through to the channel's .call (e.g.
    ``retry_deadline=0.0`` for a no-reconnect teardown flush)."""
    gcs = _gcs_channel()
    if gcs is None:
        return
    points = collect_local()
    if points:
        # call() (not notify) so the push is ordered before any subsequent
        # CollectMetrics — collect_cluster() must see its own flush.
        gcs.call(
            "ReportMetrics",
            {"reporter": reporter_id(), "points": points, "time": time.time()},
            timeout=timeout, **call_kwargs,
        )
        global _LAST_PUSH
        _LAST_PUSH = time.monotonic()


def maybe_push(min_interval_s: float = 2.0) -> bool:
    """Throttled, never-raises push — the hook the runtime piggybacks on its
    existing periodic loops (raylet report loop, worker resubscribe loop,
    task-completion flush).  Returns True if a push went out."""
    global _LAST_PUSH
    now = time.monotonic()
    with _PUSH_LOCK:
        if now - _LAST_PUSH < min_interval_s:
            return False
        _LAST_PUSH = now  # claim the slot before the RPC (other threads skip)
    try:
        push_to_gcs()
        return True
    except Exception:  # noqa: BLE001 — metrics must never take a loop down
        return False


def collect_cluster() -> List[dict]:
    """Fetch the GCS-side cluster aggregate (all reporters, latest snapshot)."""
    push_to_gcs()
    gcs = _gcs_channel()
    if gcs is None:
        return collect_local()
    return gcs.call("CollectMetrics", {}) or []


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_tags(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def prometheus_text(points: Optional[List[dict]] = None) -> str:
    """Render points in Prometheus exposition format (reference: metrics_agent.py:346)."""
    if points is None:
        points = collect_cluster()
    by_name: Dict[str, List[dict]] = {}
    for p in points:
        by_name.setdefault(p["name"], []).append(p)
    lines: List[str] = []
    for name, ps in sorted(by_name.items()):
        kind = ps[0]["kind"]
        desc = ps[0].get("description", "")
        if desc:
            lines.append(f"# HELP {name} {desc}")
        prom_kind = {"untyped": "gauge", "sketch": "summary"}.get(kind, kind)
        lines.append(f"# TYPE {name} {prom_kind}")
        for p in ps:
            tags = p.get("tags", {})
            if kind == "sketch":
                # summary convention: quantiles computed off the mergeable
                # sketch bins (so cluster-aggregate p99 is a TRUE p99, not
                # an average of per-replica p99s)
                from ray_tpu._private.latency_sketch import point_quantiles

                qs = (0.5, 0.9, 0.95, 0.99)
                for q, v in zip(qs, point_quantiles(p, qs)):
                    t = dict(tags, quantile=repr(q))
                    lines.append(f"{name}{_fmt_tags(t)} {v}")
                lines.append(f"{name}_sum{_fmt_tags(tags)} {p['sum']}")
                lines.append(f"{name}_count{_fmt_tags(tags)} {p['count']}")
            elif kind == "histogram":
                cum = 0
                for b, c in zip(p["boundaries"], p["buckets"]):
                    cum += c
                    t = dict(tags, le=repr(b))
                    lines.append(f"{name}_bucket{_fmt_tags(t)} {cum}")
                cum += p["buckets"][-1]
                t = dict(tags, le="+Inf")
                lines.append(f"{name}_bucket{_fmt_tags(t)} {cum}")
                lines.append(f"{name}_sum{_fmt_tags(tags)} {p['sum']}")
                lines.append(f"{name}_count{_fmt_tags(tags)} {p['count']}")
            else:
                lines.append(f"{name}{_fmt_tags(tags)} {p['value']}")
    return "\n".join(lines) + "\n"
