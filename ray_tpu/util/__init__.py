"""ray_tpu.util — placement groups, scheduling strategies, actor pool, queue,
collectives, metrics (reference: python/ray/util/)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.placement_group import (
    PlacementGroup,
    get_placement_group,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.queue import Queue
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "get_placement_group",
    "Queue",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
]
