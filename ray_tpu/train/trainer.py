"""Trainers: DataParallelTrainer + JaxTrainer.

reference: python/ray/train/base_trainer.py:651 (fit), data_parallel_trainer.py:26;
the controller loop mirrors Train v2's TrainController
(v2/_internal/execution/controller/controller.py:93 — run :461 polling
FailurePolicy each iteration :439). Elastic recovery restarts the whole gang
(slice-granular — a partial TPU slice is useless, SURVEY hard-part #5) and
resumes from the latest persisted checkpoint via train.get_checkpoint().
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)

logger = logging.getLogger(__name__)


class _ElasticRegrow(Exception):
    """Control-flow signal: the ScalingPolicy observed capacity for a larger
    gang mid-run; stop the current gang (checkpoint already persisted) and
    restart bigger. Not a failure — never counted against max_failures."""

    def __init__(self, current: int, target: int):
        super().__init__(f"elastic regrow {current} -> {target}")
        self.current = current
        self.target = target


class _PreemptionDrain(Exception):
    """Control-flow signal: a node hosting gang workers announced a drain
    (preemption / maintenance).  Treated exactly like an elastic resize:
    stop after the latest persisted checkpoint and restart the gang on
    surviving nodes (the scheduler already excludes DRAINING nodes).  The
    platform announced this in advance — NOT a failure, never counted
    against max_failures."""

    def __init__(self, nodes):
        super().__init__(f"gang nodes draining: {sorted(nodes)}")
        self.nodes = list(nodes)


@dataclasses.dataclass
class Result:
    """reference: ray.train.Result (air/result.py)."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint


class DataParallelTrainer:
    """SPMD gang trainer: run train_fn on every worker of the gang
    (reference: data_parallel_trainer.py:26)."""

    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        scaling_policy: Optional["ScalingPolicy"] = None,
        failure_policy: Optional["FailurePolicy"] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._train_config = train_loop_config
        self._backend_config = backend_config or self._default_backend_config
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._resume_checkpoint = resume_from_checkpoint
        self._scaling_policy = scaling_policy
        self._failure_policy = failure_policy
        # warm peer-replica ring (CheckpointConfig.peer_replicas): holder
        # actors are owned HERE, not by the executor, so a drained gang's
        # restart still finds its neighbors' host-RAM shard copies
        self._replica_holders: List[Any] = []

    # -- controller loop (v2-style) -----------------------------------------
    def fit(self) -> Result:
        from ray_tpu.train.policies import (
            DefaultFailurePolicy,
            FailureDecision,
            FixedScalingPolicy,
        )

        from ray_tpu.train._internal.checkpoint_util import join_path, makedirs_any

        from ray_tpu._private.config import global_config
        from ray_tpu.train._internal.goodput import GoodputLedger, register
        from ray_tpu.train._internal.watchdog import StepWatchdog

        name = self._run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        run_dir = join_path(self._run_config.resolved_storage_path(), name)
        makedirs_any(run_dir)
        # goodput ledger: every second of fit() lands in exactly one bucket
        # (buckets sum to the wall-clock); published to the GCS KV for
        # state.goodput()/the dashboard.  Gang bring-up counts as restore.
        ledger = register(GoodputLedger(name, job_id=self._job_id_hex()))
        ledger.start("restore")
        self.goodput_ledger = ledger
        # step watchdog: no reported result for hang_detect_timeout_s
        # triggers ONE cluster-wide diagnosis sweep per stall episode
        watchdog = StepWatchdog(global_config().hang_detect_timeout_s)
        self.last_diagnosis = None
        failure_config = self._run_config.failure_config or FailureConfig()
        failure_policy = self._failure_policy or DefaultFailurePolicy(
            max_failures=failure_config.max_failures)
        scaling_policy = self._scaling_policy or FixedScalingPolicy()
        failures = 0
        latest_ckpt = self._resume_checkpoint
        history: List[Dict[str, Any]] = []
        pending_growth: Optional[int] = None  # size a mid-run regrow observed
        growth_muted_until = 0.0              # backoff after a failed regrow

        while True:
            decision = scaling_policy.make_decision_for_non_running_worker_group(
                self._scaling.total_workers)
            n_workers = decision.num_workers
            # ANY attempt right after an elective regrow is regrow-flavored
            # (even when the policy independently agrees on the bigger size):
            # a placement failure must fall back, never kill a healthy run
            attempt_is_regrow = pending_growth is not None
            if pending_growth is not None:
                # the freed gang's resources may not be visible in the
                # cluster view yet — trust the size the running-group hook
                # just observed (a PG-ready timeout below self-corrects an
                # overestimate without counting as a training failure)
                n_workers = max(n_workers,
                                min(pending_growth, self._scaling.total_workers))
                pending_growth = None
            scaling = self._scaling
            if n_workers != scaling.total_workers:
                scaling = dataclasses.replace(
                    scaling, num_workers=n_workers, topology=None)
            ckpt_cfg = self._run_config.checkpoint_config
            if ckpt_cfg is not None and ckpt_cfg.peer_replicas:
                self._ensure_replica_holders(scaling.total_workers)
            executor = BackendExecutor(
                self._backend_config,
                scaling,
                run_dir,
                self._run_config.checkpoint_config,
                replica_holders=list(self._replica_holders),
            )
            try:
                shards = self._shard_datasets(scaling.total_workers)
                executor.start(dataset_shards=shards)
                self._push_resume_checkpoint(executor, latest_ckpt)
                executor.start_training(self._train_fn, self._train_config)
                ledger.mark("productive_step")
                watchdog.notify_progress()
                final_metrics: Dict[str, Any] = {}
                growth_check_at = time.monotonic()
                drain_check_at = time.monotonic()
                while True:
                    results, finished, error = executor.poll()
                    if results:
                        watchdog.notify_progress()
                        if ledger.current == "stall":
                            # progress resumed: close the stall span
                            ledger.mark("productive_step")
                    # persist same-round checkpoints before acting on an error
                    round_input_wait = 0.0
                    for r in results:
                        if r.get("snapshot_error") is not None:
                            # a background persist died (possibly the
                            # FINAL snapshot, with no later save() to
                            # raise from): the run continues, but the
                            # operator must know the latest checkpoint is
                            # older than they think
                            logger.error(
                                "async snapshot step %s failed on rank %s: "
                                "%s — latest restorable checkpoint is older",
                                r["metrics"].get("snapshot_step"),
                                r["rank"], r["snapshot_error"])
                            continue
                        if r.get("checkpoint") is not None:
                            ledger.mark("checkpoint")
                        ckpt = executor.persist_checkpoint(r)
                        if ckpt is not None:
                            latest_ckpt = ckpt
                        ledger.mark("productive_step")
                        # workers report data starvation as input_wait_s;
                        # ranks wait CONCURRENTLY, but the ledger is one
                        # wall-clock timeline — the round's input-bound
                        # time is the slowest worker's wait, so take the
                        # max over ranks (summing would drain productive
                        # by up to world_size x)
                        iw = (r.get("metrics") or {}).get("input_wait_s")
                        if iw:
                            round_input_wait = max(round_input_wait,
                                                   float(iw))
                        if r["rank"] == 0 and r.get("snapshot_dir") is None:
                            # snapshot-commit notifications ride the same
                            # queue but are not step results — they must
                            # not displace the last reported metrics
                            final_metrics = r["metrics"]
                            history.append(r["metrics"])
                    if round_input_wait > 0:
                        # carve once per round (the sum stays exact —
                        # reclassify moves accrued seconds)
                        ledger.reclassify("productive_step", "input_wait",
                                          round_input_wait)
                    if error:
                        raise TrainingFailedError(error)
                    if finished:
                        break
                    if watchdog.check():
                        ledger.mark("stall")
                        self._run_hang_sweep(watchdog)
                    ledger.publish()
                    # preemption watch: a drain notice on a gang node is
                    # handled like an elastic resize — this round's
                    # checkpoints are already persisted above, so restart
                    # from them on the surviving nodes
                    now = time.monotonic()
                    if now - drain_check_at >= 1.0:
                        drain_check_at = now
                        draining = self._gang_draining_nodes(executor)
                        if draining:
                            raise _PreemptionDrain(draining)
                    # elastic growth (reference: the v2 controller polls its
                    # ScalingPolicy each loop iteration — controller.py:439):
                    # when new capacity fits a bigger gang AND a checkpoint
                    # exists to resume from, checkpoint-and-regrow
                    interval = getattr(scaling_policy, "growth_poll_interval_s", 5.0)
                    now = time.monotonic()
                    if (latest_ckpt is not None and now >= growth_muted_until
                            and now - growth_check_at >= interval):
                        growth_check_at = now
                        grown = scaling_policy.make_decision_for_running_worker_group(
                            scaling.total_workers, self._scaling.total_workers)
                        if grown.num_workers > scaling.total_workers:
                            raise _ElasticRegrow(scaling.total_workers,
                                                 grown.num_workers)
                executor.shutdown()
                self._shutdown_replica_holders()
                ledger.stop()
                ledger.publish(force=True)
                return Result(
                    metrics=final_metrics, checkpoint=latest_ckpt, path=run_dir,
                    metrics_history=history,
                )
            except _PreemptionDrain as d:
                # the platform announced the node is going away: restart the
                # gang on survivors from the latest checkpoint — the drain
                # was announced in advance, so no max_failures credit burns
                ledger.mark("preemption_recovery")
                executor.shutdown()
                logger.warning(
                    "preemption drain on gang node(s) %s: restarting gang "
                    "from %s (not counted against max_failures)",
                    d.nodes, latest_ckpt)
            except _ElasticRegrow as g:
                # not a failure: stop after the checkpoint already persisted,
                # restart at the larger size the policy just observed
                ledger.mark("restore")
                executor.shutdown()
                pending_growth = g.target
                logger.info(
                    "elastic regrow: restarting gang %d -> %d workers from %s",
                    g.current, g.target, latest_ckpt)
            except TrainingFailedError as e:
                ledger.mark("restore")
                executor.shutdown()
                if attempt_is_regrow and "did not become ready" in str(e):
                    # the observed capacity evaporated before the bigger gang
                    # could place — fall back to the policy's own sizing and
                    # mute growth probes briefly so we don't thrash
                    growth_muted_until = time.monotonic() + 60.0
                    logger.warning(
                        "elastic regrow to %d workers could not place; "
                        "resuming at policy size (growth muted 60s)", n_workers)
                    continue
                failures += 1
                if failure_policy.make_decision(failures, e) == FailureDecision.RAISE:
                    self._shutdown_replica_holders()
                    ledger.stop()
                    ledger.publish(force=True)
                    return Result(
                        metrics={}, checkpoint=latest_ckpt, path=run_dir, error=e,
                        metrics_history=history,
                    )
                logger.warning(
                    "training attempt %d failed (%s); restarting gang from %s",
                    failures, e, latest_ckpt,
                )
                time.sleep(min(2.0 * failures, 10.0))

    def _ensure_replica_holders(self, n_workers: int):
        """Grow the ring of ReplicaHolder actors to the gang size.  Holder
        i receives rank (i-1)'s newest host-RAM shard copy; holders are
        spread round-robin over the currently-alive nodes (soft affinity —
        placement never fails over it) so a replica generally lands on a
        DIFFERENT node than the member it protects and survives that
        node's preemption.  A holder that still dies with its node just
        contributes nothing: the gather path skips unreachable holders
        and restore falls back to storage."""
        import ray_tpu
        from ray_tpu.train._internal.snapshot import ReplicaHolder
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        node_ids = []
        try:
            node_ids = [n["node_id"] for n in ray_tpu.nodes() or []
                        if n.get("state") == "ALIVE"]
        except Exception:  # noqa: BLE001 — placement hint only
            pass
        holder_cls = ray_tpu.remote(ReplicaHolder)
        while len(self._replica_holders) < n_workers:
            opts = {"num_cpus": 0}
            if node_ids:
                nid = node_ids[len(self._replica_holders) % len(node_ids)]
                opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                    nid, soft=True)
            self._replica_holders.append(
                holder_cls.options(**opts).remote())

    def _shutdown_replica_holders(self):
        import ray_tpu

        for h in self._replica_holders:
            try:
                ray_tpu.kill(h)
            except Exception:  # noqa: BLE001 — holder may already be gone
                pass
        self._replica_holders = []

    @staticmethod
    def _job_id_hex():
        try:
            from ray_tpu._private.worker import get_global_worker

            jid = get_global_worker().job_id
            return jid.hex() if jid is not None else None
        except Exception:  # noqa: BLE001 — clusterless unit contexts
            return None

    def _run_hang_sweep(self, watchdog):
        """One cluster-wide diagnosis sweep (fires once per stall episode):
        fold the arrival monitor's pending rounds, every process's flight-
        recorder tail, and the blocking workers' stacks into one report
        that names who is blocking what."""
        from ray_tpu._private import flight_recorder

        stalled = watchdog.stalled_for_s()
        flight_recorder.record("step", "watchdog",
                               f"stall:{stalled:.1f}s")
        logger.warning(
            "no training progress for %.1fs (hang_detect_timeout_s=%.1fs): "
            "running cluster hang sweep", stalled, watchdog.timeout_s)
        try:
            from ray_tpu.util import state

            report = state.diagnose(source="watchdog")
            self.last_diagnosis = report
            for b in report.get("blocking") or []:
                logger.error(
                    "hang diagnosis: collective group %r op %r seq %s is "
                    "blocked on rank %s (actor %s, node %s, pid %s) — "
                    "waiting %.1fs", b.get("group"), b.get("op"),
                    b.get("seq"), b.get("rank"), b.get("actor_id"),
                    b.get("node_id"), b.get("pid"), b.get("waiting_s"))
            try:
                state.record_event(
                    f"train hang sweep: {len(report.get('blocking') or [])} "
                    f"blocking member(s) after {stalled:.1f}s without "
                    "progress", severity="WARNING", source="train")
            except Exception:  # noqa: BLE001 — event record is advisory; diagnosis already logged
                pass
        except Exception:  # noqa: BLE001 — diagnosis must never kill training
            logger.exception("hang sweep failed")

    @staticmethod
    def _gang_draining_nodes(executor: BackendExecutor):
        """Gang-hosting nodes currently DRAINING in the GCS (hex ids)."""
        gang = set(getattr(executor, "worker_node_ids", None) or ())
        if not gang:
            return []
        try:
            import ray_tpu

            states = {
                (n["node_id"].hex() if hasattr(n["node_id"], "hex")
                 else str(n["node_id"])): n["state"]
                for n in ray_tpu.nodes() or []
            }
        except Exception:  # noqa: BLE001 — GCS unreachable; check next tick
            return []
        return [nid for nid in gang if states.get(nid) == "DRAINING"]

    def _push_resume_checkpoint(self, executor: BackendExecutor,
                                ckpt: Optional[Checkpoint]):
        if ckpt is None or executor.worker_group is None:
            return
        from ray_tpu._private import flight_recorder
        from ray_tpu.train._internal.checkpoint_util import set_session_resume_checkpoint

        flight_recorder.record("restore", "resume_checkpoint",
                               os.path.basename(ckpt.path))

        executor.worker_group.execute(set_session_resume_checkpoint, ckpt.path)

    def _shard_datasets(self, num_workers: int) -> Optional[List[Dict[str, Any]]]:
        """Per-worker dataset shards.  Datasets shard via streaming_split
        (ONE plan execution feeding the gang through the coordinated
        iterators; session.get_dataset_shard wraps each consumer in the
        ingest DataShard — zero-copy host batches, double-buffered device
        prefetch, measured input_wait, drain hand-back); anything exposing
        only split() falls back to materialized pieces; everything else is
        replicated."""
        if not self._datasets:
            return None
        shards: List[Dict[str, Any]] = [dict() for _ in range(num_workers)]
        for name, ds in self._datasets.items():
            if hasattr(ds, "streaming_split"):
                # generous idle window: the coordinator must survive gang
                # placement + checkpoint restore before the first pull
                # (the default 600s self-reap is tuned for interactive use)
                for i, piece in enumerate(
                        ds.streaming_split(num_workers, equal=True,
                                           idle_timeout_s=3600.0)):
                    shards[i][name] = piece
            elif hasattr(ds, "split"):
                for i, piece in enumerate(ds.split(num_workers)):
                    shards[i][name] = piece
            else:
                for i in range(num_workers):
                    shards[i][name] = ds
        return shards


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer with the JAX backend: the gang comes up with
    jax.distributed initialized so user code sees the slice's global devices
    (reference analog: TorchTrainer + _TorchBackend, torch/config.py:154)."""

    _default_backend_config = JaxConfig()

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config", jax_config or JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)
