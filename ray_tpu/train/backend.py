"""Backend plugin ABC + the JAX backend.

reference: python/ray/train/backend.py — Backend :16 / BackendConfig :32 with
hooks on_start :45, on_training_start :53, on_shutdown :49; the torch
rendezvous analog is _TorchBackend (torch/config.py:154): worker-0 address →
dist.init_process_group on every worker (:116). TPU-native: JaxConfig's
on_start publishes worker-0's coordinator address and every worker calls
jax.distributed.initialize — XLA then spans the gang's devices (SURVEY §3.4
TPU mapping).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


class BackendConfig:
    """Declarative config naming its Backend class (reference: backend.py:32)."""

    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Gang-setup hooks around the worker group (reference: backend.py:16)."""

    def on_start(self, worker_group, backend_config: BackendConfig):  # noqa: B027
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):  # noqa: B027
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):  # noqa: B027
        pass


# ---------------------------------------------------------------------------
# JAX backend
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """Config for multi-host jax gangs.

    distributed: None = auto (initialize jax.distributed iff >1 worker);
    True/False force it. On TPU pods every worker must call
    jax.distributed.initialize before touching devices.

    multislice: None = auto — when the gang's workers sit on more than one
    TPU slice (distinct TPU names from the accelerator manager), each worker
    gets the megascale env (MEGASCALE_NUM_SLICES / _SLICE_ID /
    _COORDINATOR_ADDRESS) before jax.distributed.initialize so the runtime
    brings DCN transport up between slices; pair with
    ``MeshSpec(num_slices=N)`` so only the data axis crosses DCN.
    """

    distributed: Optional[bool] = None
    coordinator_port: Optional[int] = None
    multislice: Optional[bool] = None
    # megascale DCN transport runs its own coordinator service — it must NOT
    # share the jax.distributed coordination port on the slice-0 host
    megascale_port: int = 8080

    @property
    def backend_cls(self):
        return _JaxBackend


def _pick_coordinator(port: Optional[int]):
    import socket

    # NOT gethostbyname(gethostname()) — that resolves to 127.0.1.1 on many
    # distros, which other hosts of the gang cannot reach.
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            host = s.getsockname()[0]
    except OSError:
        host = "127.0.0.1"
    if port is None:
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
    return f"{host}:{port}"


def _init_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def _get_slice_name():
    from ray_tpu._private.accelerators.tpu import TPUAcceleratorManager

    return TPUAcceleratorManager.get_current_node_tpu_name()


def _set_multislice_env(num_slices: int, slice_id: int, coordinator: str):
    """megascale contract: the TPU runtime reads these at jax.distributed
    init time to bring up DCN transport between slices."""
    import os

    os.environ["MEGASCALE_NUM_SLICES"] = str(num_slices)
    os.environ["MEGASCALE_SLICE_ID"] = str(slice_id)
    os.environ["MEGASCALE_COORDINATOR_ADDRESS"] = coordinator
    return True


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        n = len(worker_group)
        distributed = backend_config.distributed
        if distributed is None:
            distributed = n > 1
        if not distributed:
            return
        coordinator = worker_group.execute_single(
            0, _pick_coordinator, backend_config.coordinator_port
        )
        import ray_tpu

        # multislice detection: group workers by their TPU slice name; >1
        # distinct slice means gradients will cross DCN and the runtime
        # needs the megascale env on every worker BEFORE distributed init
        multislice = backend_config.multislice
        if multislice is None or multislice:
            slice_names = worker_group.execute(_get_slice_name)
            distinct = [s for s in dict.fromkeys(slice_names) if s is not None]
            if multislice and len(distinct) <= 1:
                raise ValueError(
                    "JaxConfig(multislice=True) but the gang's workers do not "
                    f"report >1 distinct TPU slice name (got {distinct or 'none'})"
                    " — megascale slice ids cannot be assigned. Check TPU_NAME /"
                    " the GCE metadata server on the workers.")
            if len(distinct) > 1:
                if any(s is None for s in slice_names):
                    raise ValueError(
                        "multi-slice gang has workers with unresolvable TPU "
                        f"slice names ({slice_names}) — a defaulted slice id "
                        "would give megascale an inconsistent topology. Check "
                        "TPU_NAME / the GCE metadata server on those workers.")
                host, port = coordinator.rsplit(":", 1)
                if int(port) == backend_config.megascale_port:
                    raise ValueError(
                        f"megascale_port {backend_config.megascale_port} "
                        "collides with the jax.distributed coordinator port — "
                        "the two coordinator services cannot share host:port")
                slice_ids = {name: i for i, name in enumerate(distinct)}
                ms_coord = f"{host}:{backend_config.megascale_port}"
                ray_tpu.get([
                    w._execute.remote(
                        _set_multislice_env, len(distinct),
                        slice_ids[slice_names[i]], ms_coord)
                    for i, w in enumerate(worker_group.workers)
                ])
        ray_tpu.get([
            w._execute.remote(_init_jax_distributed, coordinator, n, i)
            for i, w in enumerate(worker_group.workers)
        ])
