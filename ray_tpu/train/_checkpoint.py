"""Checkpoint: a directory of files, referenced by path.

reference: python/ray/train/_checkpoint.py (Checkpoint = directory + fsspec
URI). TPU-native extension (SURVEY §5 checkpoint/resume): sharded jax
checkpoints — every host writes its address-local array shards concurrently
via orbax/tensorstore (save_sharded / restore_sharded below), generalizing
the reference's single-rank upload model.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    """A reference to a directory holding checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(os.fspath(path))

    @classmethod
    def from_directory(cls, path) -> "Checkpoint":
        return cls(path)

    def as_directory(self):
        @contextlib.contextmanager
        def cm() -> Iterator[str]:
            yield self.path

        return cm()

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or os.path.join(tempfile.gettempdir(), f"ckpt_{uuid.uuid4().hex[:8]}")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def update_metadata(self, metadata: Dict[str, Any]):
        import json

        meta_path = os.path.join(self.path, ".metadata.json")
        existing = self.get_metadata()
        existing.update(metadata)
        with open(meta_path, "w") as f:
            json.dump(existing, f)

    def get_metadata(self) -> Dict[str, Any]:
        import json

        meta_path = os.path.join(self.path, ".metadata.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_sharded(state: Any, path: str, *, force: bool = True) -> str:
    """Write a pytree of (possibly sharded) jax arrays; every process writes
    its own address-local shards concurrently (orbax/tensorstore ocdbt)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path: str, target: Any = None) -> Any:
    """Restore a pytree saved by save_sharded. ``target`` (a pytree of
    ShapeDtypeStructs with shardings, or concrete arrays) drives resharding."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if target is None:
        return ckptr.restore(os.path.abspath(path))
    return ckptr.restore(os.path.abspath(path), target)
