"""Checkpoint: a directory of files, referenced by path.

reference: python/ray/train/_checkpoint.py (Checkpoint = directory + fsspec
URI). TPU-native extension (SURVEY §5 checkpoint/resume): sharded jax
checkpoints — every host writes its address-local array shards concurrently
via orbax/tensorstore (save_sharded / restore_sharded below), generalizing
the reference's single-rank upload model.
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


from ray_tpu.train._internal.checkpoint_util import (
    is_remote_path as _is_remote,
    normalize_local_path as _normalize_local,
)


class Checkpoint:
    """A reference to a directory holding checkpoint data — local or any
    fsspec URI (reference: Checkpoint = directory + fsspec URI)."""

    def __init__(self, path: str):
        p = os.fspath(path)
        self.path = p if _is_remote(p) else os.path.abspath(_normalize_local(p))

    @classmethod
    def from_directory(cls, path) -> "Checkpoint":
        return cls(path)

    def as_directory(self):
        """Context manager over a local view of the checkpoint.  For a
        remote checkpoint the download lands in a unique ``ckpt_dl_*``
        temp dir that is removed however the block exits — normal exit,
        exception, early ``break``/``return`` (generator close), or a
        download that dies mid-transfer — so no temp dirs leak."""
        @contextlib.contextmanager
        def cm() -> Iterator[str]:
            if not _is_remote(self.path):
                yield self.path
                return
            from ray_tpu.train._internal.checkpoint_util import download_dir

            # eager unique creation: collision-free under concurrent
            # callers, and the finally below owns it from the first byte
            tmp = tempfile.mkdtemp(prefix="ckpt_dl_")
            try:
                yield download_dir(self.path, tmp)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)

        return cm()

    def to_directory(self, path: Optional[str] = None) -> str:
        """Materialize the checkpoint at ``path`` (or a fresh temp dir).

        Collision-free under concurrent callers sharing one dest on one
        host: each caller stages into a unique sibling and commits by
        rename, so ``dest`` only ever holds one caller's COMPLETE copy —
        never an interleaving of two mid-flight downloads."""
        from ray_tpu.train._internal.checkpoint_util import commit_dir_atomic

        dest = path or os.path.join(tempfile.gettempdir(),
                                    f"ckpt_{uuid.uuid4().hex[:8]}")
        if not _is_remote(self.path) and os.path.abspath(dest) == self.path:
            return dest
        # replace a PRE-EXISTING dest (stale materialization), but accept a
        # concurrent caller's copy committed while we staged — same
        # checkpoint, and retiring it would yank the dir from under their
        # readers
        replace = os.path.isdir(dest)
        tmp = f"{dest}.tmp-{uuid.uuid4().hex[:8]}"
        try:
            if _is_remote(self.path):
                from ray_tpu.train._internal.checkpoint_util import download_dir

                download_dir(self.path, tmp)
            else:
                shutil.copytree(self.path, tmp)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        commit_dir_atomic(tmp, dest, replace=replace)
        return dest

    def _meta_path(self) -> str:
        if _is_remote(self.path):
            return self.path.rstrip("/") + "/.metadata.json"
        return os.path.join(self.path, ".metadata.json")

    def update_metadata(self, metadata: Dict[str, Any]):
        import json

        existing = self.get_metadata()
        existing.update(metadata)
        if _is_remote(self.path):
            import fsspec

            with fsspec.open(self._meta_path(), "w") as f:
                json.dump(existing, f)
            return
        with open(self._meta_path(), "w") as f:
            json.dump(existing, f)

    def get_metadata(self) -> Dict[str, Any]:
        import json

        meta = self._meta_path()
        if _is_remote(self.path):
            import fsspec

            fs, p = fsspec.core.url_to_fs(meta)
            if fs.exists(p):
                with fs.open(p) as f:
                    return json.load(f)
            return {}
        if os.path.exists(meta):
            with open(meta) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_sharded(state: Any, path: str, *, force: bool = True) -> str:
    """Write a pytree of (possibly sharded) jax arrays; every process writes
    its own address-local shards concurrently (orbax/tensorstore ocdbt)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()
    return path


def restore_sharded(path: str, target: Any = None) -> Any:
    """Restore a pytree saved by save_sharded. ``target`` (a pytree of
    ShapeDtypeStructs with shardings, or concrete arrays) drives resharding."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if target is None:
        return ckptr.restore(os.path.abspath(path))
    return ckptr.restore(os.path.abspath(path), target)
