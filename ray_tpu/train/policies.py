"""Pluggable scaling + failure policies for the train controller.

reference: Train v2 — TrainController holds a ScalingPolicy and a
FailurePolicy (v2/_internal/execution/controller/controller.py:110-111,
execution/scaling_policy/, execution/failure_handling/) and polls them
each control-loop iteration.

TPU semantics (SURVEY hard-parts #2/#5): gangs are slice-granular — an
elastic resize picks a whole new gang size and restarts from the latest
checkpoint (resharding forces recompilation anyway; in-place shrink of an
SPMD mesh is never worth it).
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Optional

logger = logging.getLogger(__name__)


# -- failure ---------------------------------------------------------------


class FailureDecision:
    RETRY = "RETRY"
    RAISE = "RAISE"


class FailurePolicy:
    """reference: v2 FailurePolicy ABC (failure_handling/)."""

    def make_decision(self, failure_count: int, error: BaseException) -> str:
        raise NotImplementedError


@dataclasses.dataclass
class DefaultFailurePolicy(FailurePolicy):
    """Retry up to max_failures (-1 = unlimited), then raise."""

    max_failures: int = 0

    def make_decision(self, failure_count: int, error: BaseException) -> str:
        if self.max_failures < 0 or failure_count <= self.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.RAISE


# -- scaling ---------------------------------------------------------------


@dataclasses.dataclass
class ScalingDecision:
    num_workers: int


class ScalingPolicy:
    """reference: v2 ScalingPolicy ABC (scaling_policy/)."""

    # how often the controller consults the running-group hook
    growth_poll_interval_s: float = 5.0

    def make_decision_for_non_running_worker_group(
            self, target_workers: int) -> ScalingDecision:
        """Called before each (re)start; returns the gang size to launch."""
        raise NotImplementedError

    def make_decision_for_running_worker_group(
            self, current_workers: int, target_workers: int) -> ScalingDecision:
        """Polled DURING training every control-loop interval (reference: the
        v2 controller polls its ScalingPolicy each loop iteration —
        controller.py:439). Returning a size LARGER than ``current_workers``
        triggers checkpoint-and-regrow: the gang stops after its latest
        checkpoint and restarts at the new size (in-place mesh resize is
        never worth the recompile on TPU — SURVEY hard-parts #2/#5).
        Default: keep the current size (fixed gangs never regrow)."""
        return ScalingDecision(num_workers=current_workers)


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured gang size (reference: v2 fixed policy)."""

    def make_decision_for_non_running_worker_group(self, target_workers):
        return ScalingDecision(num_workers=target_workers)


@dataclasses.dataclass
class ElasticScalingPolicy(ScalingPolicy):
    """Size the gang to what the cluster can actually supply, in
    slice-sized steps: num_workers is rounded DOWN to a multiple of
    ``workers_per_slice`` (whole slices only — a partial slice is useless),
    clamped to [min_workers, max_workers].
    """

    min_workers: int = 1
    max_workers: int = 64
    workers_per_slice: int = 1
    resources_per_worker: Optional[dict] = None

    def make_decision_for_non_running_worker_group(self, target_workers):
        import ray_tpu

        res = self.resources_per_worker or {"CPU": 1.0}
        try:
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001 — not connected; trust the target
            return ScalingDecision(num_workers=target_workers)
        fit = min(
            (math.floor(avail.get(k, 0.0) / v) for k, v in res.items() if v > 0),
            default=target_workers,
        )
        n = min(target_workers, max(fit, 0), self.max_workers)
        n = (n // self.workers_per_slice) * self.workers_per_slice
        # the floor is also slice-granular: never launch a partial slice
        min_slices = -(-self.min_workers // self.workers_per_slice)
        n = max(n, min_slices * self.workers_per_slice)
        if n != target_workers:
            logger.info("elastic scaling: gang %d -> %d workers", target_workers, n)
        return ScalingDecision(num_workers=n)

    def make_decision_for_running_worker_group(self, current_workers,
                                               target_workers):
        """Regrow when freed/added capacity fits at least one more whole
        slice (VERDICT r2 weak #7: elasticity must act mid-run, not only at
        gang (re)start)."""
        import ray_tpu

        ceiling = min(target_workers, self.max_workers)
        if current_workers >= ceiling:
            return ScalingDecision(num_workers=current_workers)
        res = self.resources_per_worker or {"CPU": 1.0}
        try:
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001
            return ScalingDecision(num_workers=current_workers)
        fit = min(
            (math.floor(avail.get(k, 0.0) / v) for k, v in res.items() if v > 0),
            default=0,
        )
        # the running gang's resources are NOT in avail: total = current + fit
        n = min(current_workers + max(fit, 0), ceiling)
        n = (n // self.workers_per_slice) * self.workers_per_slice
        if n > current_workers:
            logger.info(
                "elastic growth: capacity for %d -> %d workers appeared",
                current_workers, n)
            return ScalingDecision(num_workers=n)
        return ScalingDecision(num_workers=current_workers)
