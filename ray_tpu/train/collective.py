"""Control-plane collectives for train workers.

reference: python/ray/train/collective/collectives.py —
broadcast_from_rank_zero :23, barrier :88 (gloo-style control collectives).
Backed by the STORE collective group keyed to the training run.
"""

from __future__ import annotations

from typing import Any

from ray_tpu.train._internal.session import get_session
from ray_tpu.util import collective as col


def _ensure_group() -> str:
    s = get_session()
    if s is None:
        raise RuntimeError("not inside a training session")
    group = f"_train_{s.run_name}"
    if not col.is_group_initialized(group):
        col.init_collective_group(
            s.world_size, s.world_rank, backend=col.Backend.STORE, group_name=group
        )
    return group


def broadcast_from_rank_zero(data: Any = None) -> Any:
    """Every worker returns rank 0's ``data`` (reference: collectives.py:23)."""
    import pickle

    import numpy as np

    group = _ensure_group()
    payload = pickle.dumps(data) if get_session().world_rank == 0 else b""
    arr = np.frombuffer(payload, dtype=np.uint8)
    out = col.broadcast(arr, 0, group)
    return pickle.loads(bytes(np.asarray(out)))


def barrier() -> None:
    """Block until every worker arrives (reference: collectives.py:88)."""
    col.barrier(_ensure_group())


def allreduce_gradients(grads: Any, *, bucket_bytes: int = 4 << 20,
                        compression=None, average: bool = True) -> Any:
    """Bucketed, pipelined gradient sync across the training gang — the
    DDP-style overlap on the trainer's store path: the gradient pytree
    partitions into size-targeted buckets (reverse materialization order)
    and bucket k+1's store round is issued while bucket k's result
    uploads (``collective.allreduce_pytree``).  ``compression`` composes
    per bucket (error-feedback residuals keyed per bucket).  Returns the
    summed — or, by default, world-size-averaged — gradient tree."""
    group = _ensure_group()
    out = col.allreduce_pytree(grads, group_name=group,
                               bucket_bytes=bucket_bytes,
                               compression=compression)
    if not average:
        return out
    world = float(get_session().world_size)
    if world <= 1:
        return out
    import jax

    return jax.tree.map(lambda a: a / world, out)
