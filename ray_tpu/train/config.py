"""User-facing train configs.

reference: python/ray/air/config.py — ScalingConfig :99 (num_workers :154,
use_gpu :155, resources_per_worker :156, accelerator_type :158), RunConfig,
FailureConfig, CheckpointConfig. Per SURVEY §2.3 the rebuild adds ``use_tpu``
and ``topology`` (the reference has no use_tpu).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many training workers, with what resources, in what shape.

    TPU semantics: one worker per TPU host (SPMD gang over a slice);
    ``topology`` (e.g. "4x4x8") or ``num_workers`` sizes the gang, and
    ``chips_per_worker`` carves chips (ICI-aligned blocks of 1/2/4/8).
    """

    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # reference-compat; maps onto generic accelerator
    chips_per_worker: Optional[int] = None
    topology: Optional[str] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    accelerator_type: Optional[str] = None
    placement_strategy: str = "PACK"
    tpu_slice: Optional[str] = None  # pin the gang to one named slice

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu and "TPU" not in res:
            chips = self.chips_per_worker
            if chips is None:
                from ray_tpu._private.accelerators import get_accelerator_manager

                chips = get_accelerator_manager("TPU").get_current_node_num_accelerators() or 4
            res["TPU"] = float(chips)
        if self.accelerator_type:
            res[f"accelerator_type:{self.accelerator_type}"] = 0.001
        return res

    @property
    def total_workers(self) -> int:
        if self.topology:
            return hosts_in_topology(self.topology, self.chips_per_worker or 4)
        return self.num_workers


def hosts_in_topology(topology: str, chips_per_host: int = 4) -> int:
    """Host count for a TPU topology string like "4x4x8" (chips = product of
    dims; v4/v5p hosts expose 4 chips — reference analog:
    accelerators/tpu.py:316 get_num_workers_in_pod)."""
    import math

    dims = [int(d) for d in topology.lower().split("x")]
    chips = math.prod(dims)
    return max(1, chips // chips_per_host)


@dataclasses.dataclass
class FailureConfig:
    """reference: air/config.py FailureConfig (max_failures)."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """reference: air/config.py CheckpointConfig (num_to_keep, attr ordering).

    TPU-native extension — the continuous async snapshot subsystem
    (train/_internal/snapshot.py), engaged when the train loop reports
    state pytrees (``train.report(metrics, state=...)``):

    - ``full_snapshot_interval``: every Nth snapshot writes ALL leaves;
      the ones between are deltas that reference unchanged leaves in an
      earlier manifest, so the interval bounds how long a delta chain can
      grow (and how much retention must protect).
    - ``optimizer_state_interval``: optimizer-state leaves (top-level key
      in ``optimizer_key_prefixes``) are written every Nth snapshot only;
      in between, delta manifests reference the last written version even
      if it changed — params are still captured every snapshot.
    - ``peer_replicas``: push each member's newest host-RAM shard copy to
      a ring neighbor so a preempted member restores from peer RAM inside
      the drain window instead of from storage.
    """

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    full_snapshot_interval: int = 8
    optimizer_state_interval: int = 1
    peer_replicas: bool = False


@dataclasses.dataclass
class RunConfig:
    """reference: air/config.py RunConfig (name, storage_path, failure/ckpt)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None

    def resolved_storage_path(self) -> str:
        from ray_tpu.train._internal.checkpoint_util import (
            is_remote_path,
            normalize_local_path,
        )

        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        if is_remote_path(base):
            return base  # fsspec URI (gs://, s3://, ...): not a local path
        return os.path.abspath(normalize_local_path(base))
