"""Shared checkpoint persistence helpers (used by train's BackendExecutor and
tune's TuneController).

Storage paths may be local or any fsspec URI (gs://, s3://, ...) — the
reference persists checkpoints through fsspec the same way
(train/_internal/storage.py).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import List, Optional, Tuple

_CKPT_RE = re.compile(r"^checkpoint_(\d+)$")


def is_remote_path(path: str) -> bool:
    return "://" in str(path) and not str(path).startswith("file://")


def normalize_local_path(path: str) -> str:
    """Strip the canonical fsspec local scheme: file:///x -> /x (callers
    then treat it as a plain local path)."""
    p = str(path)
    if p.startswith("file://"):
        return p[len("file://"):] or "/"
    return p


def join_path(base: str, *names: str) -> str:
    if is_remote_path(base):
        return "/".join([str(base).rstrip("/")] + [n.strip("/") for n in names])
    return os.path.join(base, *names)


def makedirs_any(path: str) -> None:
    if is_remote_path(path):
        import fsspec

        fs, p = fsspec.core.url_to_fs(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def rmtree_any(path: str) -> None:
    if is_remote_path(path):
        import fsspec

        fs, p = fsspec.core.url_to_fs(path)
        try:
            fs.rm(p, recursive=True)
        except FileNotFoundError:
            pass
    else:
        shutil.rmtree(path, ignore_errors=True)


def upload_dir(local_src: str, dest: str) -> None:
    import fsspec

    fs, p = fsspec.core.url_to_fs(dest)
    fs.makedirs(p, exist_ok=True)
    fs.put(local_src.rstrip("/") + "/", p, recursive=True)


def download_dir(src: str, local_dest: str) -> str:
    import fsspec

    fs, p = fsspec.core.url_to_fs(src)
    os.makedirs(local_dest, exist_ok=True)
    fs.get(p.rstrip("/") + "/", local_dest.rstrip("/") + "/", recursive=True)
    return local_dest


def commit_dir_atomic(tmp: str, dest: str, replace: bool = True) -> None:
    """Move a FULLY-staged sibling dir into place.  ``dest`` is never
    observable partially written: every committed copy is complete, and a
    caller losing a concurrent race accepts the winner's complete copy
    (and cleans up its own staging) rather than fighting over the slot.

    ``replace=False`` (concurrent callers staging the SAME content, e.g.
    to_directory): an existing dest is accepted as-is — no retire/swap, so
    a reader of the winner's copy never sees the dest vanish mid-read."""
    import uuid as uuid_mod

    try:
        os.rename(tmp, dest)  # fast path: dest absent
        return
    except FileNotFoundError:
        shutil.rmtree(tmp, ignore_errors=True)
        raise  # dest's parent is gone — NOT a race; don't claim success
    except OSError:
        pass  # dest occupied
    if not replace and os.path.isdir(dest):
        shutil.rmtree(tmp, ignore_errors=True)
        return
    old = f"{dest}.old-{uuid_mod.uuid4().hex[:8]}"
    try:
        os.rename(dest, old)  # retire the previous complete contents
    except FileNotFoundError:
        # dest vanished under a concurrent committer mid-swap: retry the
        # fast path once; if their complete commit landed, accept it
        try:
            os.rename(tmp, dest)
            return
        except OSError:
            if os.path.isdir(dest):
                shutil.rmtree(tmp, ignore_errors=True)
                return
            shutil.rmtree(tmp, ignore_errors=True)
            raise
    except OSError:
        # environmental failure (permissions, I/O): the previous dest is
        # intact — surface it rather than discarding the staged copy and
        # reporting success
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    try:
        os.rename(tmp, dest)
    except OSError:
        if os.path.isdir(dest):
            # a concurrent complete commit took the slot in the window
            shutil.rmtree(tmp, ignore_errors=True)
            shutil.rmtree(old, ignore_errors=True)
            return
        os.rename(old, dest)  # roll the previous contents back
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    shutil.rmtree(old, ignore_errors=True)


def persist_staged_checkpoint(src_path: str, dest: str) -> str:
    """Move (if worker-staged) or copy a local checkpoint dir to ``dest``
    (local path or fsspec URI).

    Crash-safe replacement: the bytes are fully staged NEXT TO the
    destination first, then committed by rename (local) or by retiring the
    old prefix only after the new upload completed (remote) — a crash
    mid-persist leaves the previous checkpoint intact and restorable (the
    old rmtree-then-copy order left a corrupt "latest" instead)."""
    import uuid as uuid_mod

    staged_src = os.path.dirname(src_path).endswith(".staged")
    if is_remote_path(dest):
        import fsspec

        tag = uuid_mod.uuid4().hex[:8]
        staging = dest.rstrip("/") + f".staging-{tag}"
        upload_dir(src_path, staging)  # a crash here never touches dest
        fs, p_dest = fsspec.core.url_to_fs(dest)
        _, p_stage = fsspec.core.url_to_fs(staging)
        # retire-by-rename, never rm-then-upload: at every instant at
        # least one COMPLETE copy exists under some name (a crash between
        # the mvs leaves the previous checkpoint at .retired-* and the new
        # one at .staging-* — recoverable, nothing destroyed)
        retired = None
        if fs.exists(p_dest):
            retired = f"{p_dest}.retired-{tag}"
            fs.mv(p_dest, retired, recursive=True)
        fs.mv(p_stage, p_dest, recursive=True)
        if retired is not None:
            try:
                fs.rm(retired, recursive=True)
            except FileNotFoundError:
                pass
        if staged_src:
            shutil.rmtree(src_path, ignore_errors=True)
        return dest
    if os.path.abspath(src_path) == os.path.abspath(dest):
        return dest
    tmp = f"{dest}.tmp-{uuid_mod.uuid4().hex[:8]}"
    try:
        if staged_src:
            shutil.move(src_path, tmp)
        else:
            shutil.copytree(src_path, tmp)
    except BaseException:
        # a crash/kill mid-copy leaves only the staging dir; the previous
        # dest is untouched and still restores
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    commit_dir_atomic(tmp, dest)
    return dest


def existing_checkpoint_indices(run_dir: str) -> List[int]:
    """Indices of checkpoint_NNNNNN dirs already in a run dir (so a restarted
    gang continues the sequence instead of overwriting)."""
    if is_remote_path(run_dir):
        import fsspec

        fs, p = fsspec.core.url_to_fs(run_dir)
        try:
            names = [n.rstrip("/").rsplit("/", 1)[-1]
                     for n in fs.ls(p, detail=False)]
        except FileNotFoundError:
            return []
    elif os.path.isdir(run_dir):
        names = os.listdir(run_dir)
    else:
        return []
    out = []
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def set_session_resume_checkpoint(path: str) -> bool:
    """Runs inside a worker actor (via _execute): point the session's
    latest_checkpoint at ``path`` so train.get_checkpoint() resumes from it."""
    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train._internal import session as session_mod

    s = session_mod.get_session()
    if s is not None:
        s.latest_checkpoint = Checkpoint(path)
    return True
