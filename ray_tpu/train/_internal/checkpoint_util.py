"""Shared checkpoint persistence helpers (used by train's BackendExecutor and
tune's TuneController).

Storage paths may be local or any fsspec URI (gs://, s3://, ...) — the
reference persists checkpoints through fsspec the same way
(train/_internal/storage.py).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import List, Optional, Tuple

_CKPT_RE = re.compile(r"^checkpoint_(\d+)$")


def is_remote_path(path: str) -> bool:
    return "://" in str(path) and not str(path).startswith("file://")


def normalize_local_path(path: str) -> str:
    """Strip the canonical fsspec local scheme: file:///x -> /x (callers
    then treat it as a plain local path)."""
    p = str(path)
    if p.startswith("file://"):
        return p[len("file://"):] or "/"
    return p


def join_path(base: str, *names: str) -> str:
    if is_remote_path(base):
        return "/".join([str(base).rstrip("/")] + [n.strip("/") for n in names])
    return os.path.join(base, *names)


def makedirs_any(path: str) -> None:
    if is_remote_path(path):
        import fsspec

        fs, p = fsspec.core.url_to_fs(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def rmtree_any(path: str) -> None:
    if is_remote_path(path):
        import fsspec

        fs, p = fsspec.core.url_to_fs(path)
        try:
            fs.rm(p, recursive=True)
        except FileNotFoundError:
            pass
    else:
        shutil.rmtree(path, ignore_errors=True)


def upload_dir(local_src: str, dest: str) -> None:
    import fsspec

    fs, p = fsspec.core.url_to_fs(dest)
    fs.makedirs(p, exist_ok=True)
    fs.put(local_src.rstrip("/") + "/", p, recursive=True)


def download_dir(src: str, local_dest: str) -> str:
    import fsspec

    fs, p = fsspec.core.url_to_fs(src)
    os.makedirs(local_dest, exist_ok=True)
    fs.get(p.rstrip("/") + "/", local_dest.rstrip("/") + "/", recursive=True)
    return local_dest


def persist_staged_checkpoint(src_path: str, dest: str) -> str:
    """Move (if worker-staged) or copy a local checkpoint dir to ``dest``
    (local path or fsspec URI), replacing any stale contents."""
    if is_remote_path(dest):
        rmtree_any(dest)
        upload_dir(src_path, dest)
        if os.path.dirname(src_path).endswith(".staged"):
            shutil.rmtree(src_path, ignore_errors=True)
        return dest
    if os.path.abspath(src_path) == os.path.abspath(dest):
        return dest
    if os.path.exists(dest):
        shutil.rmtree(dest)
    if os.path.dirname(src_path).endswith(".staged"):
        shutil.move(src_path, dest)
    else:
        shutil.copytree(src_path, dest)
    return dest


def existing_checkpoint_indices(run_dir: str) -> List[int]:
    """Indices of checkpoint_NNNNNN dirs already in a run dir (so a restarted
    gang continues the sequence instead of overwriting)."""
    if is_remote_path(run_dir):
        import fsspec

        fs, p = fsspec.core.url_to_fs(run_dir)
        try:
            names = [n.rstrip("/").rsplit("/", 1)[-1]
                     for n in fs.ls(p, detail=False)]
        except FileNotFoundError:
            return []
    elif os.path.isdir(run_dir):
        names = os.listdir(run_dir)
    else:
        return []
    out = []
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def set_session_resume_checkpoint(path: str) -> bool:
    """Runs inside a worker actor (via _execute): point the session's
    latest_checkpoint at ``path`` so train.get_checkpoint() resumes from it."""
    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train._internal import session as session_mod

    s = session_mod.get_session()
    if s is not None:
        s.latest_checkpoint = Checkpoint(path)
    return True
