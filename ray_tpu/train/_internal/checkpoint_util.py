"""Shared checkpoint persistence helpers (used by train's BackendExecutor and
tune's TuneController)."""

from __future__ import annotations

import os
import re
import shutil
from typing import List, Optional, Tuple

_CKPT_RE = re.compile(r"^checkpoint_(\d+)$")


def persist_staged_checkpoint(src_path: str, dest: str) -> str:
    """Move (if worker-staged) or copy a checkpoint dir to ``dest``,
    replacing any stale contents at the destination."""
    if os.path.abspath(src_path) == os.path.abspath(dest):
        return dest
    if os.path.exists(dest):
        shutil.rmtree(dest)
    if os.path.dirname(src_path).endswith(".staged"):
        shutil.move(src_path, dest)
    else:
        shutil.copytree(src_path, dest)
    return dest


def existing_checkpoint_indices(run_dir: str) -> List[int]:
    """Indices of checkpoint_NNNNNN dirs already in a run dir (so a restarted
    gang continues the sequence instead of overwriting)."""
    if not os.path.isdir(run_dir):
        return []
    out = []
    for name in os.listdir(run_dir):
        m = _CKPT_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def set_session_resume_checkpoint(path: str) -> bool:
    """Runs inside a worker actor (via _execute): point the session's
    latest_checkpoint at ``path`` so train.get_checkpoint() resumes from it."""
    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train._internal import session as session_mod

    s = session_mod.get_session()
    if s is not None:
        s.latest_checkpoint = Checkpoint(path)
    return True
