"""Gang of training-worker actors.

reference: python/ray/train/_internal/worker_group.py — WorkerGroup :102 of
RayTrainWorker actors :19. Each worker hosts a session; the train_fn runs on
a session thread inside the actor so the driver can poll results while
training proceeds.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional


class RayTrainWorker:
    """The actor class hosting one training process
    (reference: worker_group.py:19)."""

    def __init__(self):
        self._train_thread: Optional[threading.Thread] = None

    # generic execution hooks -------------------------------------------------
    def _execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def _setup_session(self, **session_kwargs):
        from ray_tpu.train._internal import session as session_mod

        session_mod.init_session(**session_kwargs)
        return True

    def _start_training(self, train_fn: Callable, config: Optional[Dict[str, Any]]):
        from ray_tpu.train._internal import session as session_mod

        s = session_mod.get_session()
        assert s is not None, "_setup_session must run first"

        def run():
            try:
                import inspect

                if len(inspect.signature(train_fn).parameters) >= 1:
                    train_fn(config or {})
                else:
                    train_fn()
            except BaseException as e:  # noqa: BLE001
                s.error = e
            finally:
                s.finished.set()

        self._train_thread = threading.Thread(target=run, daemon=True, name="train-fn")
        self._train_thread.start()
        return True

    def _poll_results(self, timeout_s: float = 0.2):
        """Drain any reported results; returns (results, finished, error_repr).

        The driver polls this (reference: backend_executor.py:588)."""
        import queue as queue_mod

        from ray_tpu.train._internal import session as session_mod

        s = session_mod.get_session()
        if s is None:
            return [], True, None
        results = []
        try:
            results.append(s.result_queue.get(timeout=timeout_s))
            while True:
                results.append(s.result_queue.get_nowait())
        except queue_mod.Empty:
            pass
        # a finished train_fn with an async snapshot still draining is NOT
        # finished: killing the worker now would abandon the final
        # snapshot mid-persist (crash-safe, but needlessly lost) and drop
        # its commit notification
        finished = (s.finished.is_set() and s.result_queue.empty()
                    and s.persistence_idle())
        err = None
        if s.error is not None:
            import traceback

            err = "".join(traceback.format_exception(s.error))
        return results, finished, err

    def _shutdown_session(self):
        from ray_tpu.train._internal import session as session_mod

        session_mod.shutdown_session()
        return True

    def _node_info(self):
        import socket

        import ray_tpu

        ctx = ray_tpu.get_runtime_context()
        return {
            "node_id": ctx.get_node_id(),
            "hostname": socket.gethostname(),
            "tpu_ids": ray_tpu.get_tpu_ids(),
        }


class WorkerGroup:
    """N RayTrainWorker actors, optionally on a placement group
    (reference: worker_group.py:102)."""

    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 placement_group=None, max_concurrency: int = 4):
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

        self._pg = placement_group
        opts: Dict[str, Any] = {
            "num_cpus": resources_per_worker.get("CPU", 1.0),
            "resources": {k: v for k, v in resources_per_worker.items() if k != "CPU"},
            "max_concurrency": max_concurrency,
        }
        cls = ray_tpu.remote(RayTrainWorker)
        self.workers = []
        for i in range(num_workers):
            o = dict(opts)
            if placement_group is not None:
                o["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group, placement_group_bundle_index=i
                )
            self.workers.append(cls.options(**o).remote())

    def __len__(self):
        return len(self.workers)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker, return all results."""
        import ray_tpu

        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w._execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, index: int, fn: Callable, *args, **kwargs):
        import ray_tpu

        return ray_tpu.get(self.workers[index]._execute.remote(fn, *args, **kwargs))

    def call(self, method: str, *args, **kwargs) -> List[Any]:
        import ray_tpu

        return ray_tpu.get([getattr(w, method).remote(*args, **kwargs) for w in self.workers])

    def shutdown(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 — already-dead worker is
                pass            # the goal of shutdown
        self.workers = []
