"""Per-worker training session.

reference: python/ray/train/_internal/session.py — the train_fn runs in a
session thread; ``train.report(metrics, checkpoint)`` hands results to the
polling driver (backend_executor.py:588 get_next_results).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint


class TrainContext:
    """What user code can ask about its place in the gang
    (reference: ray.train.get_context())."""

    def __init__(self, session: "_TrainSession"):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_local_world_size(self) -> int:
        return self._s.local_world_size

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_experiment_name(self) -> str:
        return self._s.run_name

    def get_storage_path(self) -> Optional[str]:
        return self._s.storage_path


class _TrainSession:
    def __init__(self, *, world_size: int, world_rank: int, local_rank: int = 0,
                 local_world_size: int = 1, node_rank: int = 0,
                 run_name: str = "run", storage_path: Optional[str] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None):
        self.world_size = world_size
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.dataset_shards = dataset_shards or {}
        self.result_queue: "queue.Queue" = queue.Queue()
        self.latest_checkpoint: Optional[Checkpoint] = None
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # real buffer-empty seconds stamped by the ingest iterators
        # (data/_internal/ingest.DataShard); report() attaches the
        # accumulated value as input_wait_s so the driver's goodput ledger
        # reclassifies MEASURED starvation, not whatever user code happens
        # to report
        self._input_wait_s = 0.0
        self._input_wait_lock = threading.Lock()
        self._wrapped_shards: Dict[str, Any] = {}

    def note_input_wait(self, seconds: float) -> None:
        """Accumulate measured data-starvation seconds since the last
        report (called by the ingest iterators' buffer-empty stamps)."""
        if seconds > 0:
            with self._input_wait_lock:
                self._input_wait_s += seconds

    def consume_input_wait(self) -> float:
        with self._input_wait_lock:
            v, self._input_wait_s = self._input_wait_s, 0.0
            return v

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        # flight recorder: a report IS a step boundary — the last thing a
        # hung worker's tail shows is which step it finished (and whether a
        # checkpoint stage ran) before it stopped arriving
        from ray_tpu._private import flight_recorder

        flight_recorder.record(
            "step", "report",
            f"rank{self.world_rank}" + (":ckpt" if checkpoint else ""))
        # Persist worker-side BEFORE returning (the reference uploads from the
        # worker in report(), train/_internal/storage.py) — the caller may
        # delete its local checkpoint dir right after report() returns.
        if checkpoint is not None and self.storage_path:
            import shutil
            import tempfile
            import uuid

            from ray_tpu.train._internal.checkpoint_util import is_remote_path

            if is_remote_path(self.storage_path):
                # remote run dir: stage locally; the driver-side persist
                # uploads from here (same-machine staging — the in-process
                # cluster model; multi-host gangs upload via save_sharded)
                base = os.path.join(tempfile.gettempdir(), "ray_tpu.staged")
            else:
                base = os.path.join(self.storage_path, ".staged")
            staged = os.path.join(base, f"ckpt_{uuid.uuid4().hex[:8]}")
            shutil.copytree(checkpoint.path, staged, dirs_exist_ok=True)
            checkpoint = Checkpoint(staged)
        metrics = dict(metrics)
        iw = self.consume_input_wait()
        if iw > 0 and "input_wait_s" not in metrics:
            # measured buffer-empty seconds ride every report; an explicit
            # user-reported value wins (back-compat)
            metrics["input_wait_s"] = iw
        self.result_queue.put({"metrics": metrics, "checkpoint": checkpoint,
                               "rank": self.world_rank})

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r} was passed to the trainer")
        if not hasattr(shard, "iter_batches"):
            return shard  # opaque shard object: hand it through untouched
        wrapped = self._wrapped_shards.get(name)
        if wrapped is None or wrapped._shard is not shard:
            from ray_tpu.data._internal.ingest import DataShard

            wrapped = DataShard(shard, name=name, session=self)
            self._wrapped_shards[name] = wrapped
        return wrapped


_session: Optional[_TrainSession] = None
_session_lock = threading.Lock()


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kwargs)
        return _session


def get_session() -> Optional[_TrainSession]:
    return _session


def shutdown_session():
    global _session
    with _session_lock:
        _session = None


# -- public API (ray.train.report / get_context / get_checkpoint) -----------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a training session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError("not inside a training session")
    return TrainContext(s)


def get_checkpoint() -> Optional[Checkpoint]:
    s = get_session()
    return s.latest_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    s = get_session()
    if s is None:
        raise RuntimeError("not inside a training session")
    return s.get_dataset_shard(name)
