"""Per-worker training session.

reference: python/ray/train/_internal/session.py — the train_fn runs in a
session thread; ``train.report(metrics, checkpoint)`` hands results to the
polling driver (backend_executor.py:588 get_next_results).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint


class TrainContext:
    """What user code can ask about its place in the gang
    (reference: ray.train.get_context())."""

    def __init__(self, session: "_TrainSession"):
        self._s = session

    def get_world_size(self) -> int:
        return self._s.world_size

    def get_world_rank(self) -> int:
        return self._s.world_rank

    def get_local_rank(self) -> int:
        return self._s.local_rank

    def get_local_world_size(self) -> int:
        return self._s.local_world_size

    def get_node_rank(self) -> int:
        return self._s.node_rank

    def get_trial_name(self) -> str:
        return self._s.run_name

    def get_experiment_name(self) -> str:
        return self._s.run_name

    def get_storage_path(self) -> Optional[str]:
        return self._s.storage_path


class _TrainSession:
    def __init__(self, *, world_size: int, world_rank: int, local_rank: int = 0,
                 local_world_size: int = 1, node_rank: int = 0,
                 run_name: str = "run", storage_path: Optional[str] = None,
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 checkpoint_config: Optional[Any] = None,
                 replica_holders: Optional[list] = None,
                 gang_id: str = ""):
        self.world_size = world_size
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.dataset_shards = dataset_shards or {}
        self.result_queue: "queue.Queue" = queue.Queue()
        self.latest_checkpoint: Optional[Checkpoint] = None
        self.finished = threading.Event()
        self.error: Optional[BaseException] = None
        # real buffer-empty seconds stamped by the ingest iterators
        # (data/_internal/ingest.DataShard); report() attaches the
        # accumulated value as input_wait_s so the driver's goodput ledger
        # reclassifies MEASURED starvation, not whatever user code happens
        # to report
        self._input_wait_s = 0.0
        self._input_wait_lock = threading.Lock()
        self._wrapped_shards: Dict[str, Any] = {}
        # async snapshot subsystem (train/_internal/snapshot.py): built
        # lazily on the first report(state=...) so state-less train loops
        # never pay for it
        self.checkpoint_config = checkpoint_config
        self.replica_holders = replica_holders or []
        self.gang_id = gang_id
        self._snapshot_mgr = None
        # device telemetry: the compile observer + metrics heartbeat keep
        # a worker blocked inside one long jit compile visible to the
        # GCS's silent-reporter gauge sweep (stale-but-present instead of
        # vanishing from state.node_metrics() mid-compile)
        from ray_tpu._private import device_telemetry

        if device_telemetry.enabled():
            device_telemetry.install()
        self._last_report_t: Optional[float] = None

    # -- async snapshot subsystem -------------------------------------------
    def _snapshot_manager(self):
        if self._snapshot_mgr is not None:
            return self._snapshot_mgr
        from ray_tpu.train._internal.checkpoint_util import is_remote_path
        from ray_tpu.train._internal.snapshot import (
            SnapshotConfig,
            SnapshotManager,
        )

        if not self.storage_path or is_remote_path(self.storage_path):
            raise RuntimeError(
                "report(state=...) needs a local run dir (async per-shard "
                "snapshots commit through atomic renames + dir fsync); got "
                f"storage_path={self.storage_path!r}.  Report a staged "
                "Checkpoint instead, or point storage_path at a local/"
                "NFS mount.")
        cfg = self.checkpoint_config
        snap_cfg = SnapshotConfig(
            full_snapshot_interval=getattr(cfg, "full_snapshot_interval", 8),
            optimizer_state_interval=getattr(
                cfg, "optimizer_state_interval", 1),
            num_to_keep=getattr(cfg, "num_to_keep", None),
        )
        push = None
        if self.replica_holders:
            holders = self.replica_holders

            def push(peer: int, payload: dict) -> None:
                _call_holder(holders[peer % len(holders)], "put_replica",
                             self.world_rank, payload)

        def on_commit(snapshot_dir: str, step: int) -> None:
            # the commit rides the result queue like a reported checkpoint:
            # the driver learns the newest restorable dir without the
            # training thread ever waiting on persistence
            self.result_queue.put({
                "metrics": {"snapshot_step": step},
                "checkpoint": None,
                "snapshot_dir": snapshot_dir,
                "rank": self.world_rank,
            })

        def on_error(step: int, err: BaseException) -> None:
            # a FINAL snapshot's persist failure has no next save() to
            # raise from — ride the result queue so the driver logs it
            # loudly instead of the run finishing "clean" with a stale
            # latest checkpoint
            self.result_queue.put({
                "metrics": {"snapshot_step": step},
                "checkpoint": None,
                "snapshot_error": repr(err),
                "rank": self.world_rank,
            })

        self._snapshot_mgr = SnapshotManager(
            self.storage_path, world_rank=self.world_rank,
            world_size=self.world_size, config=snap_cfg,
            gang_id=self.gang_id, on_commit=on_commit, on_error=on_error,
            replica_push=push)
        return self._snapshot_mgr

    def restore_state(self, target: Any = None):
        """Newest restorable state, preferring a warm peer replica
        (host-RAM, seconds) over the newest committed snapshot on storage.
        Returns ``(state, step)`` or ``None`` when nothing is restorable.
        With ``target`` the state is resharded onto the target's mesh —
        any world size (elastic restore)."""
        from ray_tpu.train._internal import snapshot as snapshot_mod
        from ray_tpu.train._internal.checkpoint_util import is_remote_path

        payloads = _gather_replica_payloads(self.replica_holders)
        chosen = snapshot_mod.select_replica_set(payloads)
        latest = None
        if self.storage_path and not is_remote_path(self.storage_path):
            latest = snapshot_mod.latest_committed(self.storage_path)
        disk_step = -1
        if latest is not None:
            disk_step = snapshot_mod.load_manifest(latest)["step"]
        if chosen is not None and chosen[0]["step"] >= disk_step:
            return (snapshot_mod.restore_from_payloads(chosen, target),
                    chosen[0]["step"])
        if latest is not None:
            return snapshot_mod.restore_snapshot(latest, target), disk_step
        return None

    def persistence_idle(self) -> bool:
        """True when no async snapshot is draining — the driver must not
        declare the worker finished (and kill it) while the background
        thread is still persisting the final snapshot."""
        mgr = self._snapshot_mgr
        return mgr is None or mgr.inflight is None

    def note_input_wait(self, seconds: float) -> None:
        """Accumulate measured data-starvation seconds since the last
        report (called by the ingest iterators' buffer-empty stamps)."""
        if seconds > 0:
            with self._input_wait_lock:
                self._input_wait_s += seconds

    def consume_input_wait(self) -> float:
        with self._input_wait_lock:
            v, self._input_wait_s = self._input_wait_s, 0.0
            return v

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None,
               state: Any = None):
        # flight recorder: a report IS a step boundary — the last thing a
        # hung worker's tail shows is which step it finished (and whether a
        # checkpoint stage ran) before it stopped arriving
        from ray_tpu._private import flight_recorder

        flight_recorder.record(
            "step", "report",
            f"rank{self.world_rank}"
            + (":ckpt" if checkpoint else "")
            + (":snap" if state is not None else ""))
        if state is not None:
            # async per-shard snapshot: this call pays ONLY backpressure +
            # the device→host staging copy; persistence commits on the
            # snapshot thread and rides the result queue via on_commit
            step = self._snapshot_manager().save(state)
            metrics = dict(metrics)
            metrics.setdefault("snapshot_step", step)
        # Persist worker-side BEFORE returning (the reference uploads from the
        # worker in report(), train/_internal/storage.py) — the caller may
        # delete its local checkpoint dir right after report() returns.
        if checkpoint is not None and self.storage_path:
            import shutil
            import tempfile
            import uuid

            from ray_tpu.train._internal.checkpoint_util import is_remote_path

            if is_remote_path(self.storage_path):
                # remote run dir: stage locally; the driver-side persist
                # uploads from here (same-machine staging — the in-process
                # cluster model; multi-host gangs upload via save_sharded)
                base = os.path.join(tempfile.gettempdir(), "ray_tpu.staged")
            else:
                base = os.path.join(self.storage_path, ".staged")
            staged = os.path.join(base, f"ckpt_{uuid.uuid4().hex[:8]}")
            shutil.copytree(checkpoint.path, staged, dirs_exist_ok=True)
            checkpoint = Checkpoint(staged)
        metrics = dict(metrics)
        # device telemetry: a report carrying ``model_flops`` (the step's
        # model FLOPs) books ray_tpu_train_mfu_ratio{run} with wall = the
        # time since the previous report (a report IS the step boundary);
        # the derived ratio rides back on the metrics as ``mfu``
        now = time.monotonic()
        last, self._last_report_t = self._last_report_t, now
        mf = metrics.get("model_flops")
        if mf and last is not None and now > last:
            from ray_tpu._private import device_telemetry

            mfu = device_telemetry.note_train_step(
                self.run_name, model_flops=float(mf), wall_s=now - last)
            metrics.setdefault("mfu", round(mfu, 4))
        iw = self.consume_input_wait()
        if iw > 0 and "input_wait_s" not in metrics:
            # measured buffer-empty seconds ride every report; an explicit
            # user-reported value wins (back-compat)
            metrics["input_wait_s"] = iw
        self.result_queue.put({"metrics": metrics, "checkpoint": checkpoint,
                               "rank": self.world_rank})

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r} was passed to the trainer")
        if not hasattr(shard, "iter_batches"):
            return shard  # opaque shard object: hand it through untouched
        wrapped = self._wrapped_shards.get(name)
        if wrapped is None or wrapped._shard is not shard:
            from ray_tpu.data._internal.ingest import DataShard

            wrapped = DataShard(shard, name=name, session=self)
            self._wrapped_shards[name] = wrapped
        return wrapped


def _call_holder(holder, method: str, *args):
    """Invoke a ReplicaHolder method on a plain object (hermetic tests) or
    a ray actor handle (cluster gangs — payloads ride the object store)."""
    m = getattr(holder, method)
    if hasattr(m, "remote"):
        import ray_tpu

        return ray_tpu.get(m.remote(*args))
    return m(*args)


def _gather_replica_payloads(holders) -> list:
    """Every (rank → payload) entry across every reachable holder; a dead
    or unreachable holder contributes nothing (its payloads died with it)."""
    out = []
    for h in holders or []:
        try:
            reps = _call_holder(h, "all_replicas")
        except Exception:  # noqa: BLE001 — holder died with its node
            continue
        out.extend(reps.values())
    return out


_session: Optional[_TrainSession] = None
_session_lock = threading.Lock()


def init_session(**kwargs) -> _TrainSession:
    global _session
    with _session_lock:
        _session = _TrainSession(**kwargs)
        return _session


def get_session() -> Optional[_TrainSession]:
    return _session


def shutdown_session():
    global _session
    with _session_lock:
        if _session is not None and _session._snapshot_mgr is not None:
            try:
                # drain the in-flight persist so the last snapshot commits
                _session._snapshot_mgr.close(timeout=10.0)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        _session = None


# -- public API (ray.train.report / get_context / get_checkpoint) -----------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None,
           state: Any = None):
    s = get_session()
    if s is None:
        raise RuntimeError("ray_tpu.train.report() called outside a training session")
    s.report(metrics, checkpoint, state=state)


def restore_state(target: Any = None):
    """Newest restorable state for this gang member: a warm peer-RAM
    replica when one is fresher than storage (the preemption-drain fast
    path), else the newest committed async snapshot.  Returns
    ``(state, step)`` or ``None``; ``target`` reshards onto any mesh/world
    size (elastic restore)."""
    s = get_session()
    if s is None:
        raise RuntimeError("not inside a training session")
    return s.restore_state(target)


def get_context() -> TrainContext:
    s = get_session()
    if s is None:
        raise RuntimeError("not inside a training session")
    return TrainContext(s)


def get_checkpoint() -> Optional[Checkpoint]:
    s = get_session()
    return s.latest_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    s = get_session()
    if s is None:
        raise RuntimeError("not inside a training session")
    return s.get_dataset_shard(name)
