"""Goodput ledger: where did this job's wall-clock go?

The TPU serving/training comparison (PAPERS.md, arxiv 2605.25645) reports
cost in goodput terms — the fraction of paid wall-clock that advanced the
model — which the runtime could not compute until now.  The train
controller owns the job's wall-clock, so the ledger lives there: a state
machine that classifies EVERY second of ``fit()`` into exactly one bucket,
so the buckets always sum to the wall-clock exactly (the acceptance
invariant; no sampling, no gaps, no double counting).

Buckets:
  - ``productive_step``        workers running training steps
  - ``checkpoint``             persisting a reported checkpoint
  - ``restore``                gang bring-up / checkpoint restore / restarts
  - ``preemption_recovery``    restart caused by a platform drain notice
                               (PR 4's lifecycle — announced, not a failure)
  - ``input_wait``             data starvation workers reported
  - ``stall``                  no progress past ``hang_detect_timeout_s``
                               (the watchdog flips here until steps resume)

Time is an injected clock (monotonic by default) so classification is unit-
testable without wall-clock sleeps.  ``input_wait`` is reclassified out of
``productive_step`` post-hoc from worker-reported ``input_wait_s`` metrics
— moving time between buckets keeps the sum invariant intact.

Surfaces: ``ray_tpu_train_goodput_seconds`` (a gauge mirroring the
ledger's buckets exactly — reclassification moves seconds between
buckets, which a monotonic counter could not follow) /
``ray_tpu_train_goodput_ratio``, ``state.goodput(run)`` (published to
the GCS KV), the dashboard ``/api/goodput`` view, and a ``goodput``
block in bench.py's JSON line.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, Optional

GOODPUT_KV_PREFIX = "goodput:"

BUCKETS = (
    "productive_step",
    "checkpoint",
    "restore",
    "preemption_recovery",
    "input_wait",
    "stall",
)


class GoodputLedger:
    """Exact wall-clock partition of one training run."""

    def __init__(self, run: str, job_id: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.run = run
        self.job_id = job_id
        self._clock = clock
        self.buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._current: Optional[str] = None
        self._since: Optional[float] = None
        self._started: Optional[float] = None
        self._stopped = False
        self._last_publish = 0.0

    # -- state machine -----------------------------------------------------
    def start(self, bucket: str = "restore") -> None:
        now = self._clock()
        self._started = now
        self._since = now
        self._stopped = False
        self._current = self._check(bucket)

    def mark(self, bucket: str) -> None:
        """Transition: charge the elapsed span to the CURRENT bucket, then
        switch.  Idempotent on the same bucket (just accrues).  A no-op
        after stop(): a timed-out section thread that unblocks late must
        not resurrect accrual on a ledger whose result was discarded."""
        if self._stopped:
            return
        self._accrue(self._clock())
        self._current = self._check(bucket)

    def stop(self) -> None:
        """Final accrual; the ledger is closed — only start() reopens it."""
        self._accrue(self._clock())
        self._current = None
        self._stopped = True

    @property
    def current(self) -> Optional[str]:
        return self._current

    def _check(self, bucket: str) -> str:
        if bucket not in self.buckets:
            raise ValueError(f"unknown goodput bucket {bucket!r} "
                             f"(one of {BUCKETS})")
        return bucket

    def _sync_metric(self, *buckets: str) -> None:
        """Mirror bucket values onto the goodput gauge — the ledger owns
        the accounting; the metric surface tracks it exactly (including
        reclassification, which moves seconds between buckets)."""
        try:
            from ray_tpu._private import runtime_metrics

            for b in buckets:
                runtime_metrics.set_goodput_seconds(
                    self.run, b, self.buckets[b])
        except Exception:  # noqa: BLE001 — gauge mirror is telemetry; the ledger stays authoritative
            pass

    def _accrue(self, now: float) -> None:
        if self._current is not None and self._since is not None:
            d = now - self._since
            if d > 0:
                self.buckets[self._current] += d
                self._sync_metric(self._current)
        self._since = now

    def reclassify(self, src: str, dst: str, seconds: float) -> float:
        """Move already-accrued time between buckets (worker-reported
        input_wait carved out of productive_step).  Clamped to what ``src``
        actually holds, so the sum invariant can never break.  Returns the
        amount moved."""
        self._check(src), self._check(dst)
        moved = min(max(seconds, 0.0), self.buckets[src])
        if moved > 0:
            self.buckets[src] -= moved
            self.buckets[dst] += moved
            self._sync_metric(src, dst)
        return moved

    # -- read side ---------------------------------------------------------
    def wall_clock_s(self) -> float:
        """Exactly ``sum(buckets)`` — the invariant under test."""
        return sum(self.buckets.values())

    def snapshot(self) -> dict:
        """Accrue-to-now snapshot; ``buckets_s`` sums to ``wall_clock_s``
        exactly (unrounded)."""
        self._accrue(self._clock())
        total = self.wall_clock_s()
        productive = self.buckets["productive_step"]
        snap = {
            "run": self.run,
            "job_id": self.job_id,
            "buckets_s": dict(self.buckets),
            "wall_clock_s": total,
            "goodput_ratio": (productive / total) if total > 0 else 0.0,
            "current": self._current,
        }
        try:
            from ray_tpu._private import runtime_metrics

            runtime_metrics.set_goodput_ratio(self.run,
                                              snap["goodput_ratio"])
        except Exception:  # noqa: BLE001 — gauge mirror is telemetry; the ledger stays authoritative
            pass
        return snap

    # -- publication (state.goodput / dashboard) ---------------------------
    def publish(self, min_interval_s: float = 2.0,
                force: bool = False) -> bool:
        """Push the snapshot to the GCS KV (``goodput:<run>``) so
        ``state.goodput()`` and ``/api/goodput`` see it cluster-wide.
        Throttled; best-effort (a GCS blip never fails training)."""
        now = self._clock()
        if not force and now - self._last_publish < min_interval_s:
            return False
        self._last_publish = now
        try:
            from ray_tpu._private.worker import get_global_worker

            w = get_global_worker()
            w.gcs.call("KVPut", {
                "key": GOODPUT_KV_PREFIX + self.run,
                "value": json.dumps(self.snapshot()),
            }, timeout=5)
            return True
        except Exception:  # noqa: BLE001
            return False


# -- process-local registry (bench.py goodput block) ------------------------

_ledgers: Dict[str, GoodputLedger] = {}
_registry_lock = threading.Lock()


def register(ledger: GoodputLedger) -> GoodputLedger:
    with _registry_lock:
        _ledgers[ledger.run] = ledger
    return ledger


def goodput_snapshot() -> dict:
    """Every ledger this process created, snapshotted — bench.py embeds
    this as its ``goodput`` block."""
    with _registry_lock:
        ledgers = list(_ledgers.values())
    return {led.run: led.snapshot() for led in ledgers}
