"""Step watchdog: "nothing has happened for too long" -> one hang sweep.

The train controller polls its workers continuously; every reported result
is progress.  When no progress lands for ``hang_detect_timeout_s`` the
watchdog fires ONCE — the controller runs a cluster-wide ``state.diagnose``
sweep (arrival-monitor pending rounds, flight-recorder tails, stacks) and
flips the goodput ledger to the ``stall`` bucket — then stays quiet until
progress resumes (no sweep storm while one hang persists).

The clock is injected so tests drive stall/recovery without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable


class StepWatchdog:
    def __init__(self, timeout_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self._clock = clock
        self._last_progress = clock()
        self._fired = False

    def notify_progress(self) -> None:
        """Any worker reported a result (or training just started)."""
        self._last_progress = self._clock()
        self._fired = False

    @property
    def stalled(self) -> bool:
        return self._clock() - self._last_progress >= self.timeout_s

    def check(self) -> bool:
        """True exactly once per stall episode: the caller should sweep.
        Re-arms only after ``notify_progress``."""
        if self._fired or not self.stalled:
            return False
        self._fired = True
        return True

    def stalled_for_s(self) -> float:
        return max(self._clock() - self._last_progress, 0.0)
