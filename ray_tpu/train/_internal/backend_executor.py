"""Gang orchestration: placement group + worker group + backend hooks +
result polling + checkpoint persistence.

reference: python/ray/train/_internal/backend_executor.py — BackendExecutor
:73 (start :146, _create_placement_group :230, start_training :460,
get_next_results :588). TPU mapping (SURVEY §3.4): bundles are whole TPU
hosts; STRICT_SPREAD puts one worker per host; a tpu_slice pin keeps the
gang on one slice (the gang-scheduling atom, SURVEY hard-part #2).
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.config import CheckpointConfig, ScalingConfig

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        run_dir: str,
        checkpoint_config: Optional[CheckpointConfig] = None,
        replica_holders: Optional[List[Any]] = None,
    ):
        # ring of ReplicaHolder actors (owned by the trainer, so they
        # outlive this executor and a drained gang's restart)
        self._replica_holders = replica_holders or []
        self._backend_config = backend_config
        self._backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._run_dir = run_dir
        self._ckpt_config = checkpoint_config or CheckpointConfig()
        self._pg = None
        self.worker_group: Optional[WorkerGroup] = None
        # continue the checkpoint sequence across gang restarts (fit() builds
        # a fresh executor per attempt against the same run_dir)
        from ray_tpu.train._internal.checkpoint_util import existing_checkpoint_indices

        existing = existing_checkpoint_indices(run_dir)
        self._ckpt_counter = existing[-1] if existing else 0
        self._saved_checkpoints: List[tuple] = [
            (i, os.path.join(run_dir, f"checkpoint_{i:06d}")) for i in existing
        ]

    # -- lifecycle ----------------------------------------------------------
    def start(self, dataset_shards: Optional[List[Dict[str, Any]]] = None):
        num_workers = self._scaling.total_workers
        resources = self._scaling.worker_resources()
        self._pg = self._create_placement_group(num_workers, resources)
        self.worker_group = WorkerGroup(num_workers, resources, placement_group=self._pg)
        # rank assignment: sort by node so local ranks pack per host
        infos = self.worker_group.call("_node_info")
        node_ids = [i["node_id"] for i in infos]
        # which nodes host this gang — the trainer's drain watch compares
        # these against GCS node states to catch preemption notices mid-run
        self.worker_node_ids = [
            nid.hex() if hasattr(nid, "hex") else str(nid) for nid in node_ids
        ]
        local_rank: Dict[str, int] = {}
        node_rank: Dict[str, int] = {}
        import uuid

        import ray_tpu

        # one id per gang attempt: snapshot rank-manifests from a crashed
        # or resized earlier attempt can never merge with this gang's
        gang_id = uuid.uuid4().hex
        setup_refs = []
        for rank, (w, nid) in enumerate(zip(self.worker_group.workers, node_ids)):
            lr = local_rank.get(nid, 0)
            local_rank[nid] = lr + 1
            if nid not in node_rank:
                node_rank[nid] = len(node_rank)
            shards = dataset_shards[rank] if dataset_shards else None
            setup_refs.append(
                w._setup_session.remote(
                    world_size=num_workers,
                    world_rank=rank,
                    local_rank=lr,
                    local_world_size=0,  # patched below
                    node_rank=node_rank[nid],
                    run_name=os.path.basename(self._run_dir),
                    storage_path=self._run_dir,
                    dataset_shards=shards,
                    checkpoint_config=self._ckpt_config,
                    replica_holders=self._replica_holders,
                    gang_id=gang_id,
                )
            )
        ray_tpu.get(setup_refs)
        # local_world_size now known per node; push it
        def _set_lws(lws_by_node, nid):
            from ray_tpu.train._internal import session as session_mod

            s = session_mod.get_session()
            if s is not None:
                s.local_world_size = lws_by_node[nid]
            return True

        ray_tpu.get([
            w._execute.remote(_set_lws, dict(local_rank), nid)
            for w, nid in zip(self.worker_group.workers, node_ids)
        ])
        self._backend.on_start(self.worker_group, self._backend_config)

    def _create_placement_group(self, num_workers: int, resources: Dict[str, float]):
        from ray_tpu.util.placement_group import placement_group

        bundles = [dict(resources) for _ in range(num_workers)]
        pg = placement_group(
            bundles,
            strategy=self._scaling.placement_strategy,
            tpu_slice=self._scaling.tpu_slice,
        )
        if not pg.wait(timeout_seconds=120.0):
            raise TrainingFailedError(
                f"placement group with {num_workers}x{resources} bundles "
                "did not become ready within 120s (insufficient cluster resources?)"
            )
        return pg

    def start_training(self, train_fn: Callable, config: Optional[Dict[str, Any]] = None):
        assert self.worker_group is not None
        self._backend.on_training_start(self.worker_group, self._backend_config)
        self.worker_group.call("_start_training", train_fn, config)

    # -- result pumping -----------------------------------------------------
    def poll(self, timeout_s: float = 0.2):
        """One polling round over all workers; returns (merged_results,
        all_finished, first_error). Results reported in the same round as an
        error are still returned so their checkpoints aren't lost."""
        assert self.worker_group is not None
        outs = self.worker_group.call("_poll_results", timeout_s)
        errors = [e for (_, _, e) in outs if e]
        all_finished = all(f for (_, f, _) in outs)
        merged: List[Dict[str, Any]] = []
        for results, _, _ in outs:
            merged.extend(results)
        return merged, all_finished, (errors[0] if errors else None)

    def persist_checkpoint(self, result: Dict[str, Any]) -> Optional[Checkpoint]:
        """Copy a reported checkpoint into the run dir, enforce num_to_keep
        (reference: checkpoint_manager.py keep-top-k)."""
        snap_dir = result.get("snapshot_dir")
        if snap_dir is not None:
            # async snapshot already committed worker-side (manifest-last
            # atomic rename; retention ran there too with delta-chain
            # protection) — the driver only records the newest restorable
            # dir so gang restarts resume from it
            from ray_tpu._private import flight_recorder

            flight_recorder.record("checkpoint", "snapshot_committed",
                                   os.path.basename(snap_dir))
            return Checkpoint(snap_dir)
        ckpt: Optional[Checkpoint] = result.get("checkpoint")
        if ckpt is None:
            return None
        from ray_tpu.train._internal.checkpoint_util import (
            join_path,
            persist_staged_checkpoint,
        )

        self._ckpt_counter += 1
        dest = join_path(self._run_dir, f"checkpoint_{self._ckpt_counter:06d}")
        from ray_tpu._private import flight_recorder

        flight_recorder.record("checkpoint", "persist",
                               os.path.basename(dest))
        persist_staged_checkpoint(ckpt.path, dest)
        persisted = Checkpoint(dest)
        score_attr = self._ckpt_config.checkpoint_score_attribute
        score = result["metrics"].get(score_attr) if score_attr else self._ckpt_counter
        self._saved_checkpoints.append((score, dest))
        keep = self._ckpt_config.num_to_keep
        if keep is not None and len(self._saved_checkpoints) > keep:
            reverse = self._ckpt_config.checkpoint_score_order == "max"
            self._saved_checkpoints.sort(key=lambda t: t[0], reverse=reverse)
            from ray_tpu.train._internal.checkpoint_util import rmtree_any

            for _, path in self._saved_checkpoints[keep:]:
                rmtree_any(path)
            self._saved_checkpoints = self._saved_checkpoints[:keep]
        return persisted

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception:  # noqa: BLE001 — backend hook is user code; shutdown proceeds
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001 — PG may already be gone with the cluster
                pass
            self._pg = None
