"""Continuous async checkpointing: per-shard snapshots off the critical path.

At 100k+-accelerator scale failure is continuous and checkpoint stalls are a
first-order goodput tax (PAPERS.md, arxiv 2510.20171).  This module is the
subsystem that keeps the training step from ever waiting on storage:

  * **Staged per-shard snapshots** — at a step boundary each host performs
    ONLY the device→host copy of its address-local shards
    (:func:`stage_host_snapshot`; fresh host buffers, so a later donated
    step can never corrupt the staged bytes — the same staging discipline
    as the ingest device prefetcher's barrier hand-off).  Persistence
    (shard writes + fsync'd manifest commit) runs on a named background
    thread with at-most-one-in-flight; a second save while one is still
    draining blocks (backpressure) and the wait is metered as stall.
  * **Delta checkpoints** — per-leaf keyed-blake2b content hashes
    (``_private/prefix_hash.content_hash``) split what changed: an
    unchanged leaf's manifest entry points at the earlier checkpoint dir
    that already holds its bytes instead of rewriting them.  Entries name
    the holding dir DIRECTLY (no hop chains to walk on restore); periodic
    full snapshots (``full_snapshot_interval``) bound how far back a
    reference can reach.
  * **Crash-safe commit** — shard files first (fsync'd), then the per-rank
    manifest, then ``manifest.json`` written last via atomic rename +
    directory fsync.  A checkpoint without ``manifest.json`` never
    existed; the previous one still restores.
  * **Warm peer replicas** — each gang member pushes its newest host-RAM
    shard copy to a ring neighbor (rank ``r`` → holder ``(r+1) % world``),
    so a preempted member restores from a peer's RAM inside the drain
    window (seconds) instead of from storage (minutes).
  * **Elastic restore** — the manifest records the save-time mesh; restore
    assembles global arrays from the recorded shard indices and reshards
    onto ANY target sharding/world size, walking the target pytree in
    ``parallel/bucketing.py`` partition order so peak host memory stays
    bounded by a bucket, not the whole state.

Metrics: ``ray_tpu_train_snapshot_bytes_total{kind=full|delta|replica}``,
``ray_tpu_train_snapshot_stall_seconds_total``,
``ray_tpu_train_snapshot_inflight`` (declared in runtime_metrics.FAMILIES).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private.analysis.lock_witness import make_lock
from ray_tpu._private.prefix_hash import content_hash

logger = logging.getLogger(__name__)

MANIFEST = "manifest.json"
_RANK_MANIFEST_RE = re.compile(r"^manifest\.rank(\d+)\.json$")
_FORMAT = "ray_tpu-snapshot-v1"
_LEAF_DIR = "leaves"


# ---------------------------------------------------------------------------
# Pytree keys and staging (the only step-blocking work)
# ---------------------------------------------------------------------------


def _key_str(path) -> str:
    """Stable string key for one pytree path entry sequence."""
    parts: List[str] = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:  # pragma: no cover — future jax key kinds
            parts.append(str(p))
    return "/".join(parts) or "."


def tree_leaves_with_keys(tree: Any) -> List[Tuple[str, Any]]:
    """``[(stable_key, leaf)]`` in flattened-tree order (``jax.tree.leaves``
    order — the same order ``parallel.bucketing.partition_buckets`` indexes,
    so bucket indices address this list directly)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_key_str(path), leaf) for path, leaf in flat]


def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Shard index (tuple of slices) → ((start, stop), ...) per dim."""
    out = []
    for s, dim in zip(index, shape):
        start = 0 if s.start is None else int(s.start)
        stop = int(dim) if s.stop is None else int(s.stop)
        out.append((start, stop))
    return tuple(out)


@dataclasses.dataclass
class HostLeaf:
    """One leaf's address-local host copy: global metadata + local shards."""

    shape: Tuple[int, ...]
    dtype: str
    shards: List[Tuple[Tuple[Tuple[int, int], ...], Any]]  # (index, ndarray)

    def nbytes(self) -> int:
        return sum(int(d.nbytes) for _, d in self.shards)


@dataclasses.dataclass
class HostSnapshot:
    """Everything this process must persist for one snapshot: the staged
    (donation-safe) host copies of its address-local shards."""

    leaves: Dict[str, HostLeaf]
    step: int = 0
    world_size: int = 1

    def nbytes(self) -> int:
        return sum(leaf.nbytes() for leaf in self.leaves.values())

    def to_payload(self) -> dict:
        """Picklable form for peer-replica push (plasma/tensor channels)."""
        return {
            "step": self.step,
            "world_size": self.world_size,
            "leaves": {
                k: {"shape": list(leaf.shape), "dtype": leaf.dtype,
                    "shards": [(idx, data) for idx, data in leaf.shards]}
                for k, leaf in self.leaves.items()
            },
        }


def stage_host_snapshot(state: Any, *, step: int = 0,
                        world_size: int = 1) -> HostSnapshot:
    """Device→host copy of this process's address-local shards — the ONLY
    work on the training thread.  Copies into fresh host buffers so a
    donated next step can never alias the staged bytes (donation safety)."""
    import numpy as np

    leaves: Dict[str, HostLeaf] = {}
    for key, leaf in tree_leaves_with_keys(state):
        shards: List[Tuple[Tuple[Tuple[int, int], ...], Any]] = []
        addr = getattr(leaf, "addressable_shards", None)
        if addr:
            shape = tuple(int(d) for d in leaf.shape)
            dtype = str(np.dtype(leaf.dtype))
            for sh in addr:
                if sh.replica_id != 0:
                    continue  # one writer per distinct shard
                shards.append((_norm_index(sh.index, shape),
                               np.ascontiguousarray(np.array(sh.data))))
        else:
            arr = np.ascontiguousarray(np.array(leaf))
            shape = arr.shape
            dtype = str(arr.dtype)
            shards.append((tuple((0, int(d)) for d in arr.shape), arr))
        leaves[key] = HostLeaf(shape=shape, dtype=dtype, shards=shards)
    return HostSnapshot(leaves=leaves, step=step, world_size=world_size)


def leaf_content_hash(leaf: HostLeaf) -> int:
    """Keyed blake2b over a leaf's local shard bytes + framing (shape,
    dtype, shard indices) — stable across processes/machines."""
    frame = json.dumps([list(leaf.shape), leaf.dtype,
                        [list(map(list, idx)) for idx, _ in leaf.shards]],
                       separators=(",", ":")).encode()
    h = content_hash(b"", extra=frame)
    for _, data in leaf.shards:
        h = content_hash(memoryview(data).cast("B"),
                         extra=h.to_bytes(8, "little"))
    return h


# ---------------------------------------------------------------------------
# On-disk layout helpers
# ---------------------------------------------------------------------------


def snapshot_dir_name(step: int) -> str:
    return f"checkpoint_{step:06d}"


def _same_shard_layout(entry: dict, leaf: HostLeaf) -> bool:
    """Does a previous manifest entry cover exactly the shard indices this
    rank stages now?  False after an elastic resize re-partitioned the
    leaf — a no-hash reference would then point at wrong coverage."""
    prev_idx = sorted(tuple(map(tuple, s["index"])) for s in entry["shards"])
    cur_idx = sorted(idx for idx, _ in leaf.shards)
    return (tuple(entry["shape"]) == tuple(leaf.shape)
            and entry["dtype"] == leaf.dtype and prev_idx == cur_idx)


def _safe_name(key: str) -> str:
    """Filesystem-safe leaf file stem; a key-hash suffix keeps distinct keys
    distinct after sanitization."""
    stem = re.sub(r"[^A-Za-z0-9_.-]", "_", key)[:80]
    return f"{stem}-{content_hash(key.encode()) & 0xFFFFFFFF:08x}"


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_json_atomic(path: str, obj: dict) -> None:
    """tmp + fsync + atomic rename + dir fsync: the file either exists with
    full content or not at all."""
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def is_committed(snapshot_dir: str) -> bool:
    return os.path.exists(os.path.join(snapshot_dir, MANIFEST))


def load_manifest(snapshot_dir: str) -> dict:
    with open(os.path.join(snapshot_dir, MANIFEST)) as f:
        return json.load(f)


def latest_committed(run_dir: str) -> Optional[str]:
    """Newest snapshot dir under ``run_dir`` with a committed manifest."""
    from ray_tpu.train._internal.checkpoint_util import (
        existing_checkpoint_indices,
    )

    for idx in reversed(existing_checkpoint_indices(run_dir)):
        d = os.path.join(run_dir, snapshot_dir_name(idx))
        if is_committed(d):
            return d
    return None


def _rank_manifests(snapshot_dir: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        names = os.listdir(snapshot_dir)
    except FileNotFoundError:
        return out
    for n in names:
        m = _RANK_MANIFEST_RE.match(n)
        if m:
            out[int(m.group(1))] = os.path.join(snapshot_dir, n)
    return out


def maybe_commit_manifest(snapshot_dir: str, world_size: int) -> bool:
    """Merge per-rank manifests into ``manifest.json`` once ALL of THIS
    gang's ranks have staged theirs.  Written last and atomically — the
    commit point.  Safe under racing callers (both write identical content
    through an atomic rename).

    Rank manifests carry a ``gang`` id: a stale manifest left by a
    crashed/resized earlier attempt (different gang id, or a rank beyond
    this world size) never merges with fresh ones — it is simply ignored
    until its rank's fresh manifest overwrites it.  Returns True if the
    manifest is committed on exit."""
    if is_committed(snapshot_dir):
        return True
    ranks = _rank_manifests(snapshot_dir)
    loaded: Dict[int, dict] = {}
    for r, path in sorted(ranks.items()):
        if r >= world_size:
            continue  # stale leftover from a larger previous gang
        try:
            with open(path) as f:
                loaded[r] = json.load(f)
        except (OSError, json.JSONDecodeError):
            return False  # racing writer; a later caller commits
    if set(loaded) != set(range(world_size)):
        return False
    if len({rm.get("gang", "") for rm in loaded.values()}) != 1:
        return False  # mixed attempts: wait for fresh overwrites
    first = loaded[0]
    merged = {k: first[k] for k in
              ("format", "step", "dir", "kind", "world_size", "mesh")}
    merged["ranks"] = {str(r): rm["leaves"] for r, rm in loaded.items()}
    _write_json_atomic(os.path.join(snapshot_dir, MANIFEST), merged)
    return True


def chain_refs(manifest: dict) -> set:
    """Snapshot dir NAMES a manifest's delta entries reference for their
    bytes (excluding the manifest's own dir) — the dirs retention must
    never prune while this manifest is kept."""
    own = manifest.get("dir")
    refs = set()
    for leaves in manifest.get("ranks", {}).values():
        for entry in leaves.values():
            if entry["dir"] != own:
                refs.add(entry["dir"])
    return refs


def prune_snapshots(run_dir: str, num_to_keep: Optional[int]) -> List[str]:
    """``CheckpointConfig.num_to_keep`` retention over the run dir: keep the
    newest ``num_to_keep`` COMMITTED snapshots plus every dir a kept
    manifest's delta chain references, plus any newer uncommitted
    (in-flight) dir.  Returns the pruned dir names."""
    from ray_tpu.train._internal.checkpoint_util import (
        existing_checkpoint_indices,
    )

    if not num_to_keep or num_to_keep < 1:
        return []
    indices = existing_checkpoint_indices(run_dir)
    committed = [i for i in indices
                 if is_committed(os.path.join(run_dir, snapshot_dir_name(i)))]
    newest_committed = committed[-1] if committed else -1
    keep = {snapshot_dir_name(i) for i in committed[-num_to_keep:]}
    # protect live delta chains: anything a kept manifest references
    for name in list(keep):
        try:
            keep |= chain_refs(load_manifest(os.path.join(run_dir, name)))
        except (OSError, json.JSONDecodeError):  # racing writer; keep safe
            return []
    pruned: List[str] = []
    import shutil

    for i in indices:
        name = snapshot_dir_name(i)
        if name in keep or i > newest_committed:
            continue  # kept, referenced, or still in flight
        shutil.rmtree(os.path.join(run_dir, name), ignore_errors=True)
        pruned.append(name)
    return pruned


# ---------------------------------------------------------------------------
# Restore: assemble global arrays, reshard onto any target
# ---------------------------------------------------------------------------


def _assemble_leaf(key: str, manifest: dict, run_dir: str):
    """Global ndarray for one leaf from every rank's recorded shards (each
    entry names the dir that actually holds the bytes — no chain walking)."""
    import numpy as np

    entries = []
    for leaves in manifest["ranks"].values():
        e = leaves.get(key)
        if e is not None:
            entries.append(e)
    if not entries:
        raise KeyError(f"leaf {key!r} not present in snapshot manifest")
    shape = tuple(entries[0]["shape"])
    dtype = np.dtype(entries[0]["dtype"])
    out = np.empty(shape, dtype)
    filled = 0
    for e in entries:
        base = os.path.join(run_dir, e["dir"])
        for sh in e["shards"]:
            data = np.load(os.path.join(base, sh["file"]))
            idx = tuple(slice(a, b) for a, b in sh["index"])
            if not shape:
                out = data.astype(dtype, copy=True)
                filled = 1
                continue
            out[idx] = data
            filled += data.size
    if shape and filled < int(np.prod(shape)):
        raise ValueError(
            f"leaf {key!r}: shards cover {filled} of {int(np.prod(shape))} "
            "elements — snapshot incomplete for this world size")
    return out


def _reshard_like(arr, like):
    """Place one assembled host array like the target leaf: device_put with
    the target's sharding when it has one, else hand back host values cast
    to the target dtype."""
    import numpy as np

    sharding = getattr(like, "sharding", None)
    want_dtype = getattr(like, "dtype", None)
    if want_dtype is not None and np.dtype(want_dtype) != arr.dtype:
        arr = arr.astype(np.dtype(want_dtype))
    if sharding is not None:
        import jax

        return jax.device_put(arr, sharding)
    return arr


def _restore_into_target(target: Any, fetch: Callable[[str], Any]):
    """Rebuild ``target``'s pytree from per-key global arrays, walking the
    target in ``partition_buckets`` order so at most one bucket's worth of
    assembled host arrays is live at a time (bounded peak host memory on
    multi-GiB states)."""
    import jax

    from ray_tpu.parallel.bucketing import partition_buckets

    keyed = tree_leaves_with_keys(target)
    treedef = jax.tree_util.tree_structure(target)
    out: List[Any] = [None] * len(keyed)
    for bucket in partition_buckets(target):
        for i in bucket:
            key, like = keyed[i]
            out[i] = _reshard_like(fetch(key), like)
    return jax.tree_util.tree_unflatten(treedef, out)


def restore_snapshot(snapshot_dir: str, target: Any = None):
    """Restore a committed snapshot.

    With ``target`` (a pytree of arrays or ShapeDtypeStructs carrying
    shardings) the state is resharded onto the target's mesh — ANY world
    size, not just the save-time one (the manifest records the save-time
    mesh purely as provenance).  Without ``target`` returns a flat
    ``{leaf_key: ndarray}`` dict."""
    snapshot_dir = os.path.abspath(snapshot_dir)
    if not is_committed(snapshot_dir):
        raise FileNotFoundError(
            f"{snapshot_dir} has no {MANIFEST}: never committed (crash "
            "mid-persist?) — restore from the previous snapshot")
    manifest = load_manifest(snapshot_dir)
    run_dir = os.path.dirname(snapshot_dir)
    if target is None:
        keys = set()
        for leaves in manifest["ranks"].values():
            keys.update(leaves)
        return {k: _assemble_leaf(k, manifest, run_dir) for k in sorted(keys)}
    return _restore_into_target(
        target, lambda key: _assemble_leaf(key, manifest, run_dir))


# ---------------------------------------------------------------------------
# Warm peer replicas
# ---------------------------------------------------------------------------


class ReplicaHolder:
    """Host-RAM shard replica store for ONE ring position.  Lives outside
    the gang (the trainer owns it), so it survives gang restarts; in a
    cluster it runs as an actor and the payload rides the object store
    (plasma) — a preempted member's newest shards are a neighbor's RAM
    read away, not a storage restore."""

    def __init__(self):
        self._by_rank: Dict[int, dict] = {}

    def put_replica(self, rank: int, payload: dict) -> bool:
        payload.setdefault("rank", rank)
        cur = self._by_rank.get(rank)
        if cur is None or payload["step"] >= cur["step"]:
            self._by_rank[rank] = payload
        return True

    def get_replica(self, rank: int) -> Optional[dict]:
        return self._by_rank.get(rank)

    def all_replicas(self) -> Dict[int, dict]:
        return dict(self._by_rank)

    def newest_steps(self) -> Dict[int, int]:
        return {r: p["step"] for r, p in self._by_rank.items()}

    def clear(self) -> None:
        self._by_rank.clear()


def select_replica_set(payloads: Sequence[dict]) -> Optional[List[dict]]:
    """Newest COMPLETE replica set from a bag of per-rank payloads (as
    gathered across the ring's holders): a set is complete when one
    distinct payload exists for every save-time rank at the same step.
    Returns that set (any order) or None."""
    by_step: Dict[int, Dict[int, dict]] = {}
    for p in payloads:
        by_step.setdefault(p["step"], {})[p.get("rank", -1)] = p
    for step in sorted(by_step, reverse=True):
        ranks = by_step[step]
        world = next(iter(ranks.values()))["world_size"]
        if len(ranks) == world and set(ranks) == set(range(world)):
            return list(ranks.values())
    return None


def assemble_from_payloads(payloads: Sequence[dict]) -> Dict[str, Any]:
    """Global ``{key: ndarray}`` from a full set of per-rank replica
    payloads (all save-time ranks, same step).  Raises if coverage is
    incomplete — a partial replica set must not masquerade as a state."""
    import numpy as np

    steps = {p["step"] for p in payloads}
    if len(steps) != 1:
        raise ValueError(f"replica payloads span steps {sorted(steps)}")
    out: Dict[str, Any] = {}
    filled: Dict[str, int] = {}
    for p in payloads:
        for key, leaf in p["leaves"].items():
            shape = tuple(leaf["shape"])
            if key not in out:
                out[key] = np.empty(shape, np.dtype(leaf["dtype"]))
                filled[key] = 0
            for idx, data in leaf["shards"]:
                if not shape:
                    out[key] = np.array(data, copy=True)
                    filled[key] = 1
                    continue
                out[key][tuple(slice(a, b) for a, b in idx)] = data
                filled[key] += int(np.asarray(data).size)
    for key, arr in out.items():
        want = int(np.prod(arr.shape)) if arr.shape else 1
        if filled[key] < want:
            raise ValueError(
                f"leaf {key!r}: replica set covers {filled[key]} of {want} "
                "elements — a rank's payload is missing")
    return out


def restore_from_payloads(payloads: Sequence[dict], target: Any = None):
    """Like :func:`restore_snapshot` but from peer-RAM replica payloads:
    the preemption-drain fast path (seconds, no storage round-trip)."""
    flat = assemble_from_payloads(payloads)
    if target is None:
        return flat
    return _restore_into_target(target, lambda key: flat[key])


# ---------------------------------------------------------------------------
# The manager: staging on the caller, persistence on a named thread
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SnapshotConfig:
    """Knobs (mirrored from ``CheckpointConfig``; see train/config.py)."""

    full_snapshot_interval: int = 8
    optimizer_state_interval: int = 1
    optimizer_key_prefixes: Tuple[str, ...] = ("opt_state", "opt", "optimizer")
    num_to_keep: Optional[int] = None
    fsync: bool = True


class SnapshotManager:
    """Per-process async snapshot pipeline.

    ``save(state)`` blocks only for (a) backpressure if the previous
    snapshot is still draining (at-most-one-in-flight) and (b) the
    device→host staging copy; hashing, delta splitting, shard writes,
    manifest commit, peer push and retention all run on the named
    ``train-snapshot-r<rank>`` thread."""

    def __init__(self, run_dir: str, *, world_rank: int = 0,
                 world_size: int = 1, config: Optional[SnapshotConfig] = None,
                 gang_id: str = "",
                 clock: Callable[[], float] = time.monotonic,
                 on_commit: Optional[Callable[[str, int], None]] = None,
                 on_error: Optional[Callable[[int, BaseException],
                                             None]] = None,
                 replica_push: Optional[Callable[[int, dict], None]] = None):
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.world_rank = int(world_rank)
        self.world_size = int(world_size)
        self.config = config or SnapshotConfig()
        self.gang_id = gang_id
        self._clock = clock
        self._on_commit = on_commit
        self._on_error = on_error
        self._replica_push = replica_push
        self._lock = make_lock("SnapshotManager._lock")
        self._idle = threading.Condition(self._lock)
        self._inflight: Optional[int] = None
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        self.last_error: Optional[BaseException] = None
        # observable accounting (mirrored onto the metric families)
        self.stall_seconds = 0.0
        self.bytes_written = {"full": 0, "delta": 0, "replica": 0}
        self.snapshots_taken = 0
        # step sequence continues from the last COMMITTED snapshot — NOT
        # from raw dir listing: an uncommitted dir a faster peer already
        # created would desynchronize this rank's counter from the gang's
        # (every rank derives the same base + its own save-call count)
        self._last_full = 0
        self._prev_entries: Dict[str, dict] = {}
        self._seq = 0
        prev = latest_committed(self.run_dir)
        if prev is not None:
            man = load_manifest(prev)
            self._seq = int(man["step"])
            # previous committed entries for THIS rank (delta base)
            self._prev_entries = dict(
                man["ranks"].get(str(self.world_rank), {}))
        self._thread = threading.Thread(
            target=self._drain, daemon=True,
            name=f"train-snapshot-r{self.world_rank}")
        self._thread.start()

    # -- critical-path side --------------------------------------------------
    def save(self, state: Any) -> int:
        """Stage and enqueue one snapshot; returns its step index.  The
        only step-blocking costs are backpressure + the device→host copy,
        both metered into the stall counter."""
        from ray_tpu._private import runtime_metrics

        if self.last_error is not None:
            err, self.last_error = self.last_error, None
            raise RuntimeError(
                f"previous async snapshot failed: {err!r}") from err
        t0 = self._clock()
        with self._idle:
            while self._inflight is not None:
                self._idle.wait(timeout=0.05)
            self._seq += 1
            step = self._seq
            self._inflight = step
        runtime_metrics.set_snapshot_inflight(1)
        try:
            snap = stage_host_snapshot(state, step=step,
                                       world_size=self.world_size)
            kind = "full"
            if self._prev_entries and (
                    step - self._last_full
                    < self.config.full_snapshot_interval):
                kind = "delta"
            else:
                self._last_full = step
            self._queue.put((snap, kind))
        except BaseException:
            # a failed staging must not leave the pipeline marked busy
            # (every later save() would deadlock on the backpressure wait)
            # nor consume the step number — the gang's ranks count save
            # calls in lockstep, and a one-rank gap would block every
            # later commit barrier
            with self._idle:
                self._seq = step - 1
                self._inflight = None
                self._idle.notify_all()
            runtime_metrics.set_snapshot_inflight(0)
            raise
        stall = self._clock() - t0
        self.stall_seconds += stall
        self.snapshots_taken += 1
        runtime_metrics.add_snapshot_stall(stall)
        return step

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until no snapshot is in flight (tests / clean shutdown)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._inflight is not None:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(timeout=0.05 if remaining is None
                                else min(remaining, 0.05))
        return True

    def close(self, timeout: float = 30.0) -> None:
        self.wait(timeout)
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=5.0)

    @property
    def inflight(self) -> Optional[int]:
        return self._inflight

    # -- background side -----------------------------------------------------
    def _drain(self) -> None:
        from ray_tpu._private import runtime_metrics

        while True:
            job = self._queue.get()
            if job is None:
                return
            snap, kind = job
            try:
                self._push_replica(snap)
                self._persist(snap, kind)
            except BaseException as e:  # noqa: BLE001 — surfaced on next save()
                self.last_error = e
                logger.exception("async snapshot step %d failed", snap.step)
                if self._on_error is not None:
                    # a FINAL failed snapshot has no next save() to raise
                    # from — the callback lets the session surface it to
                    # the driver instead of the run finishing "clean"
                    try:
                        self._on_error(snap.step, e)
                    except Exception:  # noqa: BLE001 — reporting is best-effort
                        pass
            finally:
                with self._idle:
                    self._inflight = None
                    self._idle.notify_all()
                runtime_metrics.set_snapshot_inflight(0)

    def _push_replica(self, snap: HostSnapshot) -> None:
        """Newest host-RAM copy to the ring neighbor BEFORE storage: the
        drain-window restore path must not wait for the shard writes.
        Best-effort — a dead neighbor holder degrades the replica ring,
        it must never fail the durable persist behind it."""
        if self._replica_push is None:
            return
        from ray_tpu._private import runtime_metrics

        peer = (self.world_rank + 1) % max(self.world_size, 1)
        payload = snap.to_payload()
        payload["rank"] = self.world_rank
        try:
            self._replica_push(peer, payload)
        except Exception:  # noqa: BLE001 — ring degraded, persist continues
            logger.warning(
                "peer-replica push to ring position %d failed for step %d "
                "(holder dead with its node?); storage persist continues",
                peer, snap.step, exc_info=True)
            return
        n = snap.nbytes()
        self.bytes_written["replica"] += n
        runtime_metrics.inc_snapshot_bytes("replica", n)

    def _persist(self, snap: HostSnapshot, kind: str) -> None:
        import numpy as np

        from ray_tpu._private import flight_recorder, runtime_metrics

        d = os.path.join(self.run_dir, snapshot_dir_name(snap.step))
        leaf_dir = os.path.join(d, _LEAF_DIR)
        os.makedirs(leaf_dir, exist_ok=True)
        dir_name = snapshot_dir_name(snap.step)
        flight_recorder.record("checkpoint", "snapshot_persist",
                               f"{dir_name}:{kind}")
        entries: Dict[str, dict] = {}
        written = 0
        opt_skip = self._optimizer_skip(snap.step)
        for key, leaf in snap.leaves.items():
            prev = self._prev_entries.get(key)
            if kind == "delta" and prev is not None:
                if opt_skip and self._is_optimizer_key(key) \
                        and _same_shard_layout(prev, leaf):
                    # every-N policy: reference the last written version
                    # without even hashing (the skip is the point).  Only
                    # valid while this rank's shard layout matches the
                    # referenced entry's — after an elastic resize the
                    # old coverage would be wrong, so fall through and
                    # write.  (The hash path below is resize-safe on its
                    # own: shard indices are part of the hash framing.)
                    entries[key] = dict(prev)
                    continue
                h = leaf_content_hash(leaf)
                if h == prev["hash"]:
                    entries[key] = dict(prev)
                    continue
            else:
                h = leaf_content_hash(leaf)
            files = []
            for i, (idx, data) in enumerate(leaf.shards):
                fname = f"{_LEAF_DIR}/{_safe_name(key)}" \
                        f".r{self.world_rank}.s{i}.npy"
                path = os.path.join(d, fname)
                with open(path, "wb") as f:
                    np.save(f, data)
                    f.flush()
                    if self.config.fsync:
                        os.fsync(f.fileno())
                written += int(data.nbytes)
                files.append({"file": fname,
                              "index": [list(p) for p in idx]})
            entries[key] = {"shape": list(leaf.shape), "dtype": leaf.dtype,
                            "hash": h, "dir": dir_name, "kind": "written",
                            "shards": files}
        if self.config.fsync:
            _fsync_dir(leaf_dir)
        rank_manifest = {
            "format": _FORMAT, "step": snap.step, "dir": dir_name,
            "kind": kind, "world_size": snap.world_size,
            "gang": self.gang_id, "mesh": self._mesh_info(),
            "leaves": entries,
        }
        _write_json_atomic(
            os.path.join(d, f"manifest.rank{self.world_rank}.json"),
            rank_manifest)
        self.bytes_written[kind] += written
        runtime_metrics.inc_snapshot_bytes(kind, written)
        self._prev_entries = entries
        if maybe_commit_manifest(d, snap.world_size):
            flight_recorder.record("checkpoint", "snapshot_commit", dir_name)
            prune_snapshots(self.run_dir, self.config.num_to_keep)
            if self._on_commit is not None:
                self._on_commit(d, snap.step)

    def _is_optimizer_key(self, key: str) -> bool:
        head = key.split("/", 1)[0]
        return head in self.config.optimizer_key_prefixes

    def _optimizer_skip(self, step: int) -> bool:
        n = self.config.optimizer_state_interval
        return n > 1 and step % n != 0

    @staticmethod
    def _mesh_info() -> dict:
        """Save-time mesh provenance (restore never needs it — elastic
        restore reshards onto the target — but operators do)."""
        try:
            import jax

            return {"devices": jax.device_count(),
                    "process_count": jax.process_count(),
                    "backend": jax.default_backend()}
        except Exception:  # noqa: BLE001 — manifest survives without jax
            return {}
