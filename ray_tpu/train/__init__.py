"""ray_tpu.train — SPMD gang training on TPU slices.

reference: python/ray/train/ (SURVEY §2.3, §3.4). The JaxTrainer brings a
gang of one-worker-per-TPU-host actors up with jax.distributed initialized,
runs the user train loop on each, pumps ``report()`` results back, persists
checkpoints (sharded via orbax), and restarts the whole gang on failure.
"""

from ray_tpu.train._checkpoint import Checkpoint, restore_sharded, save_sharded
from ray_tpu.train._internal.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
    restore_state,
)
from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.policies import (
    DefaultFailurePolicy,
    ElasticScalingPolicy,
    FailureDecision,
    FailurePolicy,
    FixedScalingPolicy,
    ScalingDecision,
    ScalingPolicy,
)
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, Result

__all__ = [
    "Checkpoint",
    "save_sharded",
    "restore_sharded",
    "report",
    "restore_state",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "TrainContext",
    "Backend",
    "BackendConfig",
    "JaxConfig",
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "DataParallelTrainer",
    "JaxTrainer",
    "Result",
    "FailurePolicy",
    "DefaultFailurePolicy",
    "FailureDecision",
    "ScalingPolicy",
    "ScalingDecision",
    "FixedScalingPolicy",
    "ElasticScalingPolicy",
]
