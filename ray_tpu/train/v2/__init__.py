"""Train v2 API: controller-process training (reference: ray.train.v2).

The v1 surface (ray_tpu.train.JaxTrainer) runs its control loop in the
driver; v2 runs it in a controller ACTOR — detachable, re-attachable, with
live status — while reusing the same BackendExecutor/WorkerGroup/policies
underneath (reference: v2/api/data_parallel_trainer.py over
controller/controller.py:93).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.train.v2.controller import (
    TrainControllerActor,
    TrainControllerHandle,
)


class JaxTrainer:
    """v2 trainer: same constructor surface as v1's JaxTrainer, but fit()
    drives a controller actor. ``detached_name`` makes the controller a
    named detached actor so training survives the driver (re-join with
    ``JaxTrainer.attach(name)``)."""

    def __init__(self, train_loop_per_worker, *, detached_name: Optional[str] = None,
                 **trainer_kwargs):
        self._train_fn = train_loop_per_worker
        self._kwargs = trainer_kwargs
        self._detached_name = detached_name

    def _controller(self):
        import cloudpickle

        import ray_tpu

        fn, kwargs = self._train_fn, self._kwargs

        def make_trainer():
            from ray_tpu.train.trainer import JaxTrainer as V1JaxTrainer

            return V1JaxTrainer(fn, **kwargs)

        blob = cloudpickle.dumps(make_trainer)
        opts = {"num_cpus": 0.5, "max_concurrency": 4}
        if self._detached_name:
            opts.update(name=self._detached_name, lifetime="detached")
        actor_cls = ray_tpu.remote(TrainControllerActor).options(**opts)
        return actor_cls.remote(blob)

    def fit(self):
        handle = self.fit_async()
        return handle.result()

    def fit_async(self) -> TrainControllerHandle:
        """Launch without blocking; poll ``handle.status()`` / await
        ``handle.result()`` (reference: v2 async controller execution)."""
        actor = self._controller()
        return TrainControllerHandle(actor, actor.run.remote())

    @staticmethod
    def attach(name: str) -> TrainControllerHandle:
        return TrainControllerHandle.attach(name)


__all__ = [
    "JaxTrainer",
    "TrainControllerActor",
    "TrainControllerHandle",
]
