"""Train v2: the control loop in its own PROCESS (a controller actor).

reference: python/ray/train/v2/_internal/execution/controller/controller.py:93
(TrainController — run :461, _run_control_loop_iteration :439) — v2's core
move is taking the control loop out of the driver: the controller owns the
worker group, polls Scaling/Failure policies, and survives the driver. Here
the controller is an actor; ``lifetime="detached"`` + a name makes training
driver-failure-proof, and ``TrainControllerHandle.attach`` re-joins it.

The loop body is the battle-tested v1 controller (trainer.DataParallelTrainer
.fit); v2 adds the process split, live status, and attach/result semantics.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

RUNNING = "RUNNING"
FINISHED = "FINISHED"
ERRORED = "ERRORED"


class TrainControllerActor:
    """Runs the training control loop; deploy via ``ray_tpu.remote``.

    ``trainer_blob``: cloudpickled zero-arg callable returning a configured
    v1 ``DataParallelTrainer`` (pickled as a thunk so constructing heavy
    objects happens inside the controller process, not the driver).
    """

    def __init__(self, trainer_blob: bytes):
        import cloudpickle

        self._make_trainer = cloudpickle.loads(trainer_blob)
        self._state = RUNNING
        self._result = None
        self._error: Optional[str] = None
        self._latest_metrics: Dict[str, Any] = {}
        self._iterations = 0
        self._lock = threading.Lock()
        self._started = time.time()

    def run(self):
        """Execute the control loop to completion; returns the Result.

        get_status stays responsive while this runs because the controller
        actor is deployed with max_concurrency > 1 (the v2 trainer does)."""
        try:
            trainer = self._make_trainer()
            result = trainer.fit()
            with self._lock:
                self._state = ERRORED if result.error is not None else FINISHED
                self._result = result
                self._latest_metrics = result.metrics or {}
                self._iterations = len(result.metrics_history)
                if result.error is not None:
                    self._error = repr(result.error)
            return result
        except BaseException as e:  # noqa: BLE001
            with self._lock:
                self._state = ERRORED
                self._error = repr(e)
            raise

    def get_status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "error": self._error,
                "latest_metrics": dict(self._latest_metrics),
                "iterations": self._iterations,
                "uptime_s": time.time() - self._started,
            }

    def get_result(self):
        with self._lock:
            if self._result is None:
                if self._state == ERRORED:
                    raise RuntimeError(
                        f"training controller failed: {self._error}")
                raise RuntimeError(f"training still {self._state}")
            return self._result


class TrainControllerHandle:
    """Driver-side handle: await the result, poll status, or re-attach."""

    def __init__(self, actor, run_ref):
        self._actor = actor
        self._run_ref = run_ref

    def status(self) -> Dict[str, Any]:
        import ray_tpu

        return ray_tpu.get(self._actor.get_status.remote())

    def result(self, timeout: Optional[float] = None):
        import ray_tpu

        if self._run_ref is not None:
            return ray_tpu.get(self._run_ref, timeout=timeout)
        # attached after the fact: poll until the controller stores a result
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            st = self.status()
            if st["state"] != RUNNING:
                return ray_tpu.get(self._actor.get_result.remote())
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("training still running")
            time.sleep(0.5)

    @classmethod
    def attach(cls, name: str) -> "TrainControllerHandle":
        """Re-join a named (detached) controller after a driver restart
        (reference: v2's driver-independence story)."""
        import ray_tpu

        return cls(ray_tpu.get_actor(name), None)
