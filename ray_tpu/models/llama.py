"""Llama-family decoder LM, TPU-first.

Design choices (vs the reference's torch/CUDA delegation):
  - pure-functional params pytree; layers *stacked* on a leading axis and
    iterated with `lax.scan` — one compiled layer body, O(1) compile time in
    depth, and `jax.checkpoint` inside the scan body gives per-layer
    rematerialisation (HBM ⇄ FLOPs trade, SURVEY.md "HBM bandwidth").
  - GQA attention via ray_tpu.ops (pallas flash kernel on TPU; ring
    attention over the "context" mesh axis for long sequences).
  - sharding expressed as a PartitionSpec tree (param_specs) over the
    canonical mesh axes (data/fsdp/context/tensor); XLA inserts all
    collectives (all-gather for fsdp params, psum for tensor partials).
  - matmuls in bf16 with fp32 accumulation (MXU native); norms/softmax fp32.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import multi_head_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.parallel.mesh import BATCH_AXES

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        hq = self.n_heads * self.head_dim
        hkv = self.n_kv_heads * self.head_dim
        per_layer = d * hq + 2 * d * hkv + hq * d + 3 * d * f + 2 * d
        head = 0 if self.tie_embeddings else d * v
        return v * d + self.n_layers * per_layer + d + head

    # ---- presets ----
    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama3_70b(cls, **kw) -> "LlamaConfig":
        return cls(
            dim=8192, n_layers=80, n_heads=64, n_kv_heads=8, ffn_dim=28672, **kw
        )

    @classmethod
    def llama32_1b(cls, **kw) -> "LlamaConfig":
        return cls(
            dim=2048, n_layers=16, n_heads=32, n_kv_heads=8, ffn_dim=8192,
            tie_embeddings=True, **kw
        )

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-sized config: runs in milliseconds on a CPU mesh."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("dim", 128)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 4)
        kw.setdefault("n_kv_heads", 2)
        kw.setdefault("ffn_dim", 256)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("compute_dtype", jnp.float32)
        return cls(**kw)


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialize a stacked-layers params pytree."""
    d, f = cfg.dim, cfg.ffn_dim
    hq = cfg.n_heads * cfg.head_dim
    hkv = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    std = 0.02
    out_std = std / math.sqrt(2 * L)
    ks = jax.random.split(key, 10)
    dt = cfg.param_dtype

    def norm_(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dt)

    params: Params = {
        "embed": norm_(ks[0], (cfg.vocab_size, d), std),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "wq": norm_(ks[1], (L, d, hq), std),
            "wk": norm_(ks[2], (L, d, hkv), std),
            "wv": norm_(ks[3], (L, d, hkv), std),
            "wo": norm_(ks[4], (L, hq, d), out_std),
            "mlp_norm": jnp.ones((L, d), dt),
            "w_gate": norm_(ks[5], (L, d, f), std),
            "w_up": norm_(ks[6], (L, d, f), std),
            "w_down": norm_(ks[7], (L, f, d), out_std),
        },
        "final_norm": jnp.ones((d,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_(ks[8], (d, cfg.vocab_size), std)
    return params


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpec tree matching init_params' structure.

    Megatron-style TP over the "tensor" axis; parameters additionally sharded
    over "fsdp" on their non-tensor dim (XLA all-gathers per layer).
    """
    specs: Params = {
        "embed": P("tensor", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tensor"),
            "wk": P(None, "fsdp", "tensor"),
            "wv": P(None, "fsdp", "tensor"),
            "wo": P(None, "tensor", "fsdp"),
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tensor"),
            "w_up": P(None, "fsdp", "tensor"),
            "w_down": P(None, "tensor", "fsdp"),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tensor")
    return specs


def _constraint(x, spec, mesh):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, spec))


def _layer(cfg: LlamaConfig, x, lp, cos, sin, mesh, context_parallel):
    """One transformer block. x: [B, S, D]."""
    b, s, d = x.shape
    cdt = cfg.compute_dtype
    seq_axis = "context" if context_parallel else None

    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"].astype(cdt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ lp["wk"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ lp["wv"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = _constraint(q, P(BATCH_AXES, seq_axis, "tensor", None), mesh)
    k = _constraint(k, P(BATCH_AXES, seq_axis, "tensor", None), mesh)
    if context_parallel:
        # positions are global: offset by this shard's slot in the ring.
        # rope is applied inside the shard_map so positions line up.
        def attn_fn(q_, k_, v_):
            idx = lax.axis_index("context")
            s_local = q_.shape[1]
            pos = idx * s_local + jnp.arange(s_local)
            q_r = apply_rope(q_, cos, sin, positions=pos)
            k_r = apply_rope(k_, cos, sin, positions=pos)
            return ring_attention(q_r, k_r, v_, "context", causal=True)

        attn = jax.shard_map(
            attn_fn,
            mesh=mesh,
            axis_names={"context"},
            in_specs=(P(None, "context"),) * 3,
            out_specs=P(None, "context"),
        )(q, k, v)
    else:
        q = apply_rope(q, cos[:s], sin[:s])
        k = apply_rope(k, cos[:s], sin[:s])
        attn = multi_head_attention(q, k, v, causal=True)
    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    x = x + (attn @ lp["wo"].astype(cdt))
    x = _constraint(x, P(BATCH_AXES, seq_axis, None), mesh)

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    gate = h @ lp["w_gate"].astype(cdt)
    up = h @ lp["w_up"].astype(cdt)
    ffn = (jax.nn.silu(gate) * up) @ lp["w_down"].astype(cdt)
    x = x + ffn
    return _constraint(x, P(BATCH_AXES, seq_axis, None), mesh)


def forward(
    cfg: LlamaConfig,
    params: Params,
    tokens: jnp.ndarray,
    *,
    mesh: Optional[Mesh] = None,
    context_parallel: bool = False,
    rope_cache: Optional[tuple] = None,
) -> jnp.ndarray:
    """Token ids [B, S] -> logits [B, S, V] (fp32)."""
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    seq_axis = "context" if context_parallel else None
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.compute_dtype)
    x = _constraint(x, P(BATCH_AXES, seq_axis, None), mesh)

    layer = partial(_layer, cfg, cos=cos, sin=sin, mesh=mesh, context_parallel=context_parallel)
    if cfg.remat:
        layer = jax.checkpoint(layer, policy=jax.checkpoint_policies.nothing_saveable)

    def body(x, lp):
        return layer(x, lp), None

    x, _ = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cfg.compute_dtype)).astype(jnp.float32)
    return _constraint(logits, P(BATCH_AXES, seq_axis, "tensor"), mesh)


def loss_fn(
    cfg: LlamaConfig,
    params: Params,
    tokens: jnp.ndarray,
    *,
    loss_mask: Optional[jnp.ndarray] = None,
    mesh: Optional[Mesh] = None,
    context_parallel: bool = False,
    rope_cache: Optional[tuple] = None,
) -> jnp.ndarray:
    """Next-token cross-entropy (mean over unmasked positions)."""
    logits = forward(
        cfg, params, tokens, mesh=mesh, context_parallel=context_parallel,
        rope_cache=rope_cache,
    )
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt_logit
    if loss_mask is not None:
        m = loss_mask[:, 1:].astype(nll.dtype)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (6N + attention term) for MFU math."""
    n = cfg.num_params
    attn = 12 * cfg.n_layers * cfg.dim * seq_len  # 2*2*3 * L * d * s (fwd+bwd, causal half)
    return 6.0 * n + attn
