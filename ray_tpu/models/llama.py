"""Llama-family decoder LM, TPU-first.

Design choices (vs the reference's torch/CUDA delegation):
  - pure-functional params pytree; layers *stacked* on a leading axis and
    iterated with `lax.scan` — one compiled layer body, O(1) compile time in
    depth, and `jax.checkpoint` inside the scan body gives per-layer
    rematerialisation (HBM ⇄ FLOPs trade, SURVEY.md "HBM bandwidth").
  - GQA attention via ray_tpu.ops (pallas flash kernel on TPU; ring
    attention over the "context" mesh axis for long sequences).
  - sharding expressed as a PartitionSpec tree (param_specs) over the
    canonical mesh axes (data/fsdp/context/tensor); XLA inserts all
    collectives (all-gather for fsdp params, psum for tensor partials).
  - matmuls in bf16 with fp32 accumulation (MXU native); norms/softmax fp32.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import multi_head_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.parallel.mesh import BATCH_AXES

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True
    # what the per-layer checkpoint keeps for the backward pass:
    #   "full" — nothing_saveable: minimum HBM, one extra fwd of recompute
    #   "attn" — keep the attention block's output (checkpoint_name'd):
    #            +B*S*D bf16 per layer of HBM buys skipping the flash-
    #            attention recompute in bwd — the best FLOPs/byte trade here
    #   "dots" — dots_with_no_batch_dims_saveable: every GEMM output kept;
    #            fastest bwd, fits only when activations are small vs HBM
    remat_policy: str = "full"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        hq = self.n_heads * self.head_dim
        hkv = self.n_kv_heads * self.head_dim
        per_layer = d * hq + 2 * d * hkv + hq * d + 3 * d * f + 2 * d
        head = 0 if self.tie_embeddings else d * v
        return v * d + self.n_layers * per_layer + d + head

    # ---- presets ----
    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(**kw)

    @classmethod
    def llama3_70b(cls, **kw) -> "LlamaConfig":
        return cls(
            dim=8192, n_layers=80, n_heads=64, n_kv_heads=8, ffn_dim=28672, **kw
        )

    @classmethod
    def llama32_1b(cls, **kw) -> "LlamaConfig":
        return cls(
            dim=2048, n_layers=16, n_heads=32, n_kv_heads=8, ffn_dim=8192,
            tie_embeddings=True, **kw
        )

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test-sized config: runs in milliseconds on a CPU mesh."""
        kw.setdefault("vocab_size", 256)
        kw.setdefault("dim", 128)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 4)
        kw.setdefault("n_kv_heads", 2)
        kw.setdefault("ffn_dim", 256)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("compute_dtype", jnp.float32)
        return cls(**kw)


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialize a stacked-layers params pytree."""
    d, f = cfg.dim, cfg.ffn_dim
    hq = cfg.n_heads * cfg.head_dim
    hkv = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    std = 0.02
    out_std = std / math.sqrt(2 * L)
    ks = jax.random.split(key, 10)
    dt = cfg.param_dtype

    def norm_(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dt)

    params: Params = {
        "embed": norm_(ks[0], (cfg.vocab_size, d), std),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "wq": norm_(ks[1], (L, d, hq), std),
            "wk": norm_(ks[2], (L, d, hkv), std),
            "wv": norm_(ks[3], (L, d, hkv), std),
            "wo": norm_(ks[4], (L, hq, d), out_std),
            "mlp_norm": jnp.ones((L, d), dt),
            "w_gate": norm_(ks[5], (L, d, f), std),
            "w_up": norm_(ks[6], (L, d, f), std),
            "w_down": norm_(ks[7], (L, f, d), out_std),
        },
        "final_norm": jnp.ones((d,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_(ks[8], (d, cfg.vocab_size), std)
    return params


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpec tree matching init_params' structure.

    Megatron-style TP over the "tensor" axis; parameters additionally sharded
    over "fsdp" on their non-tensor dim (XLA all-gathers per layer).
    """
    specs: Params = {
        "embed": P("tensor", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tensor"),
            "wk": P(None, "fsdp", "tensor"),
            "wv": P(None, "fsdp", "tensor"),
            "wo": P(None, "tensor", "fsdp"),
            "mlp_norm": P(None, None),
            "w_gate": P(None, "fsdp", "tensor"),
            "w_up": P(None, "fsdp", "tensor"),
            "w_down": P(None, "tensor", "fsdp"),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tensor")
    return specs


def inference_param_specs(cfg: LlamaConfig) -> Params:
    """TP-only PartitionSpec tree for serving (no fsdp axis: inference has no
    optimizer state to shard, and per-layer fsdp all-gathers would serialize
    the latency-critical decode step).

    Megatron layout over the "tensor" axis: attention/FFN projections are
    column-sharded on their output dim and row-sharded back (XLA inserts the
    psum), the embedding table is vocab-sharded, and the LM head column-
    sharded so logits come out vocab-sharded too.
    reference: llm/_internal/serve/deployments/llm/vllm/vllm_models.py:177-186
    (TP degree wired from engine_kwargs into the vLLM engine).
    """
    specs: Params = {
        "embed": P("tensor", None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tensor"),
            "wk": P(None, None, "tensor"),
            "wv": P(None, None, "tensor"),
            "wo": P(None, "tensor", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "tensor"),
            "w_up": P(None, None, "tensor"),
            "w_down": P(None, "tensor", None),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tensor")
    return specs


def kv_cache_spec() -> Dict[str, P]:
    """KV cache [L, B, S, n_kv, hd] shards the kv-head axis over "tensor",
    matching wk/wv column sharding — cache writes and attention reads then
    never reshard."""
    spec = P(None, None, None, "tensor", None)
    return {"k": spec, "v": spec}


def _constraint(x, spec, mesh):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, spec))


def _remat_policy(cfg):
    policies = {
        "full": jax.checkpoint_policies.nothing_saveable,
        "attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    try:
        return policies[cfg.remat_policy]
    except KeyError:
        raise ValueError(
            f"remat_policy={cfg.remat_policy!r} — must be one of {sorted(policies)}"
        ) from None


def _layer(cfg: LlamaConfig, x, lp, cos, sin, mesh, context_parallel):
    """One transformer block. x: [B, S, D]."""
    b, s, d = x.shape
    cdt = cfg.compute_dtype
    seq_axis = "context" if context_parallel else None

    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"].astype(cdt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ lp["wk"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ lp["wv"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = _constraint(q, P(BATCH_AXES, seq_axis, "tensor", None), mesh)
    k = _constraint(k, P(BATCH_AXES, seq_axis, "tensor", None), mesh)
    if context_parallel:
        # positions are global: offset by this shard's slot in the ring.
        # rope is applied inside the shard_map so positions line up.
        def attn_fn(q_, k_, v_):
            idx = lax.axis_index("context")
            s_local = q_.shape[1]
            pos = idx * s_local + jnp.arange(s_local)
            q_r = apply_rope(q_, cos, sin, positions=pos)
            k_r = apply_rope(k_, cos, sin, positions=pos)
            return ring_attention(q_r, k_r, v_, "context", causal=True)

        from ray_tpu.util.jax_compat import shard_map as _shard_map

        attn = _shard_map(
            attn_fn,
            mesh=mesh,
            axis_names={"context"},
            in_specs=(P(None, "context"),) * 3,
            out_specs=P(None, "context"),
        )(q, k, v)
    else:
        q = apply_rope(q, cos[:s], sin[:s])
        k = apply_rope(k, cos[:s], sin[:s])
        attn = multi_head_attention(q, k, v, causal=True)
    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    attn = checkpoint_name(attn, "attn_out")
    x = x + (attn @ lp["wo"].astype(cdt))
    x = _constraint(x, P(BATCH_AXES, seq_axis, None), mesh)

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    gate = h @ lp["w_gate"].astype(cdt)
    up = h @ lp["w_up"].astype(cdt)
    ffn = (jax.nn.silu(gate) * up) @ lp["w_down"].astype(cdt)
    x = x + ffn
    return _constraint(x, P(BATCH_AXES, seq_axis, None), mesh)


def forward(
    cfg: LlamaConfig,
    params: Params,
    tokens: jnp.ndarray,
    *,
    mesh: Optional[Mesh] = None,
    context_parallel: bool = False,
    rope_cache: Optional[tuple] = None,
) -> jnp.ndarray:
    """Token ids [B, S] -> logits [B, S, V] (fp32)."""
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    seq_axis = "context" if context_parallel else None
    # See models/moe.py: the table's fsdp sharding must not propagate through
    # the token gather (involuntary-full-remat reshard otherwise). Vocab dim
    # stays TP-sharded; the embed dim is all-gathered over fsdp for the gather.
    emb = _constraint(params["embed"], P("tensor", None), mesh)
    x = jnp.take(emb, tokens, axis=0).astype(cfg.compute_dtype)
    x = _constraint(x, P(BATCH_AXES, seq_axis, None), mesh)

    layer = partial(_layer, cfg, cos=cos, sin=sin, mesh=mesh, context_parallel=context_parallel)
    if cfg.remat:
        layer = jax.checkpoint(layer, policy=_remat_policy(cfg))

    def body(x, lp):
        return layer(x, lp), None

    x, _ = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cfg.compute_dtype)).astype(jnp.float32)
    return _constraint(logits, P(BATCH_AXES, seq_axis, "tensor"), mesh)


def loss_fn(
    cfg: LlamaConfig,
    params: Params,
    tokens: jnp.ndarray,
    *,
    loss_mask: Optional[jnp.ndarray] = None,
    mesh: Optional[Mesh] = None,
    context_parallel: bool = False,
    rope_cache: Optional[tuple] = None,
) -> jnp.ndarray:
    """Next-token cross-entropy (mean over unmasked positions)."""
    logits = forward(
        cfg, params, tokens, mesh=mesh, context_parallel=context_parallel,
        rope_cache=rope_cache,
    )
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt_logit
    if loss_mask is not None:
        m = loss_mask[:, 1:].astype(nll.dtype)
        return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(nll)


# ---------------------------------------------------------------------------
# Incremental decoding (KV cache) — the compute path under ray_tpu.llm's
# engine (reference analog: the vLLM engine Ray LLM delegates to,
# llm/_internal/serve/deployments/llm/vllm/).  TPU-first: static cache
# shapes [L, B, S_max, ...], per-slot scatter via .at[] (lowers to
# dynamic-update-slice), one fused decode program for the whole batch.
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: LlamaConfig, max_batch: int, max_seq: int,
                  dtype=None) -> Dict[str, jnp.ndarray]:
    """Static-shape KV cache for `max_batch` sequence slots."""
    dtype = dtype or cfg.compute_dtype
    shape = (cfg.n_layers, max_batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def prefill(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
            rope_cache: Optional[tuple] = None):
    """Full-sequence forward that also returns per-layer K/V.

    tokens [B, S] -> (logits [B, S, V] fp32, kv {"k","v"} [L, B, S, kv, hd])
    """
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    b, s = tokens.shape
    cdt = cfg.compute_dtype
    x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)

    def body(x, lp):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"].astype(cdt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos[:s], sin[:s])
        k = apply_rope(k, cos[:s], sin[:s])
        attn = multi_head_attention(q, k, v, causal=True)
        attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
        x = x + (attn @ lp["wo"].astype(cdt))
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        ffn = (jax.nn.silu(h @ lp["w_gate"].astype(cdt))
               * (h @ lp["w_up"].astype(cdt))) @ lp["w_down"].astype(cdt)
        return x + ffn, (k, v)

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cdt)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def write_cache_slot(cache: Dict[str, jnp.ndarray], kv: Dict[str, jnp.ndarray],
                     slot: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Write one prefilled sequence (batch dim 1) into cache slot `slot`."""
    out = {}
    for name in ("k", "v"):
        out[name] = lax.dynamic_update_slice(
            cache[name], kv[name].astype(cache[name].dtype),
            (0, slot, 0, 0, 0))
    return out


def decode_step(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                cache: Dict[str, jnp.ndarray], lengths: jnp.ndarray,
                rope_cache: Optional[tuple] = None):
    """One-token decode for every cache slot.

    tokens [B] int32 (the token at position lengths[b]); lengths [B] int32.
    Returns (logits [B, V] fp32, updated cache).  Slots with lengths == 0
    compute garbage but write only their own slot — callers mask them.

    The cache rides the layer scan as CARRY with per-layer one-token DUS
    writes — scanning it as xs/ys would RESTACK the whole [L, B, S, kv, hd]
    cache every step (a full cache write per token: measured 22.3 ->
    8.1 ms/token-step at batch 32 on v5e, ~71% of the params+cache-read
    HBM roofline).
    """
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    b = tokens.shape[0]
    s_max = cache["k"].shape[2]
    cdt = cfg.compute_dtype
    group = cfg.n_heads // cfg.n_kv_heads
    x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)  # [B, d]
    batch_idx = jnp.arange(b)
    pos_mask = (jnp.arange(s_max)[None, :] <= lengths[:, None])  # [B, S]

    def body(carry, inp):
        x, ck_all, cv_all = carry
        lp, li = inp
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"].astype(cdt)).reshape(b, 1, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(cdt)).reshape(b, 1, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(cdt)).reshape(b, 1, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin, positions=lengths[:, None])[:, 0]  # [B,nh,hd]
        k = apply_rope(k, cos, sin, positions=lengths[:, None])[:, 0]
        ck_all = ck_all.at[li, batch_idx, lengths].set(k.astype(ck_all.dtype))
        cv_all = cv_all.at[li, batch_idx, lengths].set(v[:, 0].astype(cv_all.dtype))
        ck = ck_all[li]
        cv = cv_all[li]
        # GQA attention against the cache, masked to valid positions.
        # bf16 operands + fp32 ACCUMULATION (preferred_element_type): an
        # .astype(f32) on the cache would materialize a full-span fp32 copy
        # per decode step — 2x the HBM bytes of the weight-bound roofline
        qg = q.reshape(b, cfg.n_kv_heads, group, cfg.head_dim)
        scores = jnp.einsum("bkgd,bskd->bkgs", qg, ck,
                            preferred_element_type=jnp.float32)
        scores = scores / math.sqrt(cfg.head_dim)
        scores = jnp.where(pos_mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bkgs,bskd->bkgd", probs.astype(ck.dtype), cv,
                          preferred_element_type=jnp.float32)
        attn = attn.reshape(b, cfg.n_heads * cfg.head_dim).astype(cdt)
        x = x + attn @ lp["wo"].astype(cdt)
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        ffn = (jax.nn.silu(h @ lp["w_gate"].astype(cdt))
               * (h @ lp["w_up"].astype(cdt))) @ lp["w_down"].astype(cdt)
        return (x + ffn, ck_all, cv_all), None

    (x, ks, vs), _ = lax.scan(
        body, (x, cache["k"], cache["v"]),
        (params["layers"], jnp.arange(cfg.n_layers)))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cdt)).astype(jnp.float32)  # [B, V]
    return logits, {"k": ks, "v": vs}


# ---------------------------------------------------------------------------
# Paged KV cache programs (reference capability boundary: the paged-attention
# engine Ray LLM gets by delegating to vLLM, vllm_models.py:177-186 — here
# TPU-native).  The cache is a POOL of fixed-size blocks laid out
# [L, num_blocks, block_size, kv*hd]: block-major, so one block is a
# contiguous [bs, kv*hd] slab — a table gather moves whole slabs, a pallas
# page DMA lands on perfect (sublane, lane) tiles with zero padding, and a
# kv head is a lane-aligned column slice; each sequence owns a host-side
# list of block ids, shipped to the device as a padded block TABLE [B, W].
# All shapes static: W is bucketed, so programs recompile only per (B, W)
# bucket.
#
# The pool rides the layer scan as CARRY; every per-layer touch is a SINGLE
# fused XLA gather/scatter whose leading index is the (scalar) layer id —
# `pool[li, table]` / `pool.at[li, blk, off].set(...)` — so no layer slice
# is ever materialized and the pool is never restacked.  (The previous
# xs/ys design restacked the full pool every token-step: measured 6.8 ms of
# the 11.5 ms/token-step at b32 on v5e — see benchmarks/paged_bisect.py.)
# Sharding: the kv-head axis shards over "tensor" exactly as the dense
# cache, layer axis over "pipeline", block/table axes replicated.
# ---------------------------------------------------------------------------


def init_paged_kv_cache(cfg: LlamaConfig, num_blocks: int, block_size: int,
                        dtype=None) -> Dict[str, jnp.ndarray]:
    """Block-pool KV cache shared by all sequences; HBM ∝ blocks in use."""
    dtype = dtype or cfg.compute_dtype
    shape = (cfg.n_layers, num_blocks, block_size,
             cfg.n_kv_heads * cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def paged_kv_cache_spec() -> Dict[str, P]:
    # the folded kv*hd dim shards over "tensor" as contiguous head groups
    spec = P(None, None, None, "tensor")
    return {"k": spec, "v": spec}


@dataclasses.dataclass(frozen=True)
class TPPlan:
    """Planner-routed tensor-parallel collectives for the paged inference
    programs.

    When a ``TPPlan`` is passed to ``decode_step_paged`` /
    ``decode_window_paged`` / ``prefill_chunk_paged``, the two per-layer
    partial-sum reductions (attention output @ wo and FFN @ w_down) run as
    EXPLICIT shard_map programs executing the α-β planner's chosen
    algorithm instead of GSPMD's implicit psum: ``flat`` (one fused psum —
    the latency-bound small-message winner), ``ring`` (psum_scatter +
    all_gather, bandwidth-optimal), or ``tree`` (recursive
    halving-doubling via ppermute, pow2 worlds).  ``flat`` and ``ring``
    are bit-identical to the implicit-psum path (same per-rank partials,
    same summation order); ``tree`` pairs ranks differently and may
    differ in float ULPs.

    ``overlap`` chains each collective's output through a scalar token
    with ``lax.optimization_barrier`` — identity numerics, but the
    explicit stage boundary lets XLA's latency-hiding scheduler start the
    next layer's compute under the allreduce, exactly as
    ``make_train_step`` does for bucketed gradient syncs.
    """

    mesh: Any
    algorithm: str = "flat"
    overlap: bool = True
    axis: str = "tensor"


def _tp_allreduce_local(v, axis: str, world: int, algorithm: str):
    """In-shard_map allreduce of a partial sum ``v`` by the planned
    algorithm.  Ring/tree operate on the trailing (feature) dim, which the
    engine-mesh validation guarantees divides by the world size."""
    if world <= 1:
        return v
    if algorithm == "ring":
        s = lax.psum_scatter(v, axis, scatter_dimension=v.ndim - 1,
                             tiled=True)
        return lax.all_gather(s, axis, axis=v.ndim - 1, tiled=True)
    if algorithm == "tree" and not (world & (world - 1)):
        # recursive halving-doubling over the flattened payload (adapted
        # from xla_group.build_tree_allreduce): log2(n) pairwise halving
        # rounds, then doubling in bit order
        shp = v.shape
        cur = v.reshape(-1)
        idx = lax.axis_index(axis)
        mask = world // 2
        perms = []
        while mask >= 1:
            perms.append([(i, i ^ mask) for i in range(world)])
            mask //= 2
        for perm in perms:
            m = perm[0][0] ^ perm[0][1]
            half = cur.shape[0] // 2
            lo, hi = cur[:half], cur[half:]
            bit = (idx & m) != 0
            send = jnp.where(bit, lo, hi)
            keep = jnp.where(bit, hi, lo)
            cur = keep + lax.ppermute(send, axis, perm)
        for perm in reversed(perms):
            m = perm[0][0] ^ perm[0][1]
            bit = (idx & m) != 0
            recv = lax.ppermute(cur, axis, perm)
            cur = jnp.where(bit, jnp.concatenate([recv, cur]),
                            jnp.concatenate([cur, recv]))
        return cur.reshape(shp)
    return lax.psum(v, axis)


def _tp_out_proj(a, w, tp_plan: Optional["TPPlan"], token):
    """Output projection ``a @ w`` with the contraction dim sharded over
    the tensor axis.  ``tp_plan=None``: plain matmul (GSPMD inserts the
    psum implicitly).  Otherwise the per-rank partial matmul + planned
    allreduce run explicitly under shard_map, and when overlapping the
    result is chained through ``token`` (optimization_barrier — identity
    numerics, explicit stage boundary).  Returns (out, token)."""
    if tp_plan is None:
        return a @ w, token
    mesh, axis = tp_plan.mesh, tp_plan.axis
    world = int(mesh.shape.get(axis, 1))
    if world <= 1:
        return a @ w, token
    from ray_tpu.util.jax_compat import shard_map as _shard_map

    def body(a_, w_):
        return _tp_allreduce_local(a_ @ w_, axis, world, tp_plan.algorithm)

    a_spec = P(*([None] * (a.ndim - 1) + [axis]))
    out = _shard_map(body, mesh=mesh, in_specs=(a_spec, P(axis, None)),
                     out_specs=P(*([None] * a.ndim)),
                     check_rep=False)(a, w)
    if token is not None:
        out, token = lax.optimization_barrier((out, token))
    return out, token


def _paged_attend(cfg: LlamaConfig, q, ck, cv, span_mask):
    """GQA attention of q [B, T, nh, hd] against gathered spans ck/cv
    [B, S, kv, hd]; span_mask [B, T, S] True = visible."""
    b, t = q.shape[:2]
    group = cfg.n_heads // cfg.n_kv_heads
    qg = q.reshape(b, t, cfg.n_kv_heads, group, cfg.head_dim)
    # bf16 operands, fp32 accumulate: no full-span fp32 cache copies
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, ck,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(cfg.head_dim)
    scores = jnp.where(span_mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bkgts,bskd->btkgd", probs.astype(ck.dtype), cv,
                      preferred_element_type=jnp.float32)
    return attn.reshape(b, t, cfg.n_heads * cfg.head_dim)


def paged_kernel_supported(cfg: LlamaConfig) -> bool:
    """Whether the fused pallas paged-attention kernel applies: TPU backend,
    lane-aligned head_dim, and the kernel import available."""
    if jax.default_backend() != "tpu":
        return False
    if cfg.head_dim % 128:
        return False
    try:
        from ray_tpu.ops.paged_attention import (  # noqa: F401
            paged_decode_attention,
        )
    except ImportError:
        return False
    return True


def decode_step_paged(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                      pool: Dict[str, jnp.ndarray], table: jnp.ndarray,
                      lengths: jnp.ndarray,
                      rope_cache: Optional[tuple] = None,
                      use_kernel: bool = False, mesh=None,
                      kernel_interpret: bool = False,
                      tp_plan: Optional[TPPlan] = None):
    """One-token decode for every slot, KV in a paged pool.

    tokens [B] int32; table [B, W] block ids covering each slot's sequence
    (host guarantees coverage through position lengths[b]); lengths [B].
    ``use_kernel`` (static): pallas fused paged-attention — reads ONLY each
    sequence's live pages instead of materializing the XLA block gather
    (measured on v5e b32: 5.2 vs 5.3 ms/token-step at span 256, 8.0 vs 17.4
    at span 1024 — benchmarks/paged_bisect.py).  With ``mesh``, the kernel
    runs under shard_map with kv heads sharded over the "tensor" axis, so
    it composes with TP.  With ``tp_plan``, the per-layer partial-sum
    reductions route through the planner's chosen algorithm explicitly
    (see :class:`TPPlan`).  Returns (logits [B, V] fp32, updated pool).
    """
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    b = tokens.shape[0]
    bs = pool["k"].shape[2]
    w = table.shape[1]
    cdt = cfg.compute_dtype
    bidx = jnp.arange(b)
    cur_blk = table[bidx, lengths // bs]  # [B] physical block of the write
    cur_off = lengths % bs
    if not use_kernel:  # the kernel masks from `lengths` internally
        span_mask = (jnp.arange(w * bs)[None, None, :]
                     <= lengths[:, None, None])  # [B, 1, W*bs]
    x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)
    overlap = tp_plan is not None and tp_plan.overlap

    def body(carry, inp):
        # pool rides the CARRY; the scalar layer id fuses into every
        # gather/scatter's index vector, so no [li] slice is materialized
        # and no per-step restack happens (see module comment)
        if overlap:
            x, pk_all, pv_all, tok = carry
        else:
            (x, pk_all, pv_all), tok = carry, None
        lp, li = inp
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"].astype(cdt)).reshape(b, 1, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(cdt)).reshape(b, 1, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(cdt)).reshape(b, 1, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin, positions=lengths[:, None])
        k = apply_rope(k, cos, sin, positions=lengths[:, None])[:, 0]
        pk_all = pk_all.at[li, cur_blk, cur_off].set(
            k.reshape(b, -1).astype(pk_all.dtype))
        pv_all = pv_all.at[li, cur_blk, cur_off].set(
            v[:, 0].reshape(b, -1).astype(pv_all.dtype))
        if use_kernel:
            from ray_tpu.ops.paged_attention import paged_decode_attention

            kern = partial(paged_decode_attention,
                           interpret=kernel_interpret)
            if mesh is not None and mesh.shape.get("tensor", 1) > 1:
                from jax.experimental.shard_map import shard_map

                t = P(None, None, None, "tensor")
                kern = shard_map(
                    kern, mesh=mesh,
                    in_specs=(P(None, "tensor", None), t, t, P(), P(), P()),
                    out_specs=P(None, "tensor"), check_rep=False)
            attn = kern(q[:, 0], pk_all, pv_all, li, table, lengths)
        else:
            ck = pk_all[li, table].reshape(b, w * bs, cfg.n_kv_heads,
                                           cfg.head_dim)
            cv = pv_all[li, table].reshape(b, w * bs, cfg.n_kv_heads,
                                           cfg.head_dim)
            attn = _paged_attend(cfg, q, ck, cv, span_mask)[:, 0]
        out, tok = _tp_out_proj(attn.astype(cdt), lp["wo"].astype(cdt),
                                tp_plan, tok)
        x = x + out
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        gated = (jax.nn.silu(h @ lp["w_gate"].astype(cdt))
                 * (h @ lp["w_up"].astype(cdt)))
        ffn, tok = _tp_out_proj(gated, lp["w_down"].astype(cdt),
                                tp_plan, tok)
        carry = (x + ffn, pk_all, pv_all)
        return (carry + (tok,) if overlap else carry), None

    carry0 = (x, pool["k"], pool["v"])
    if overlap:
        carry0 = carry0 + (jnp.zeros((), cfg.compute_dtype),)
    carry, _ = lax.scan(
        body, carry0, (params["layers"], jnp.arange(cfg.n_layers)))
    x, ks, vs = carry[:3]
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cdt)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def decode_window_paged(cfg: LlamaConfig, params: Params,
                        tokens: jnp.ndarray, pool: Dict[str, jnp.ndarray],
                        table: jnp.ndarray, lengths: jnp.ndarray,
                        rope_cache: Optional[tuple] = None,
                        pos_limit: Optional[int] = None,
                        tp_plan: Optional[TPPlan] = None):
    """Multi-token decode window for every slot (speculative verification).

    tokens [B, T]: per-slot window starting at positions ``lengths[b]``
    (token j lands at global position lengths[b] + j).  Writes each
    window token's KV into the pool at its position — positions at or
    past ``pos_limit`` (the engine's max_seq) redirect to sink block 0
    instead of clamping, so a near-the-end slot can never clobber its own
    live KV with a duplicate scatter index — then attends causally over
    the table span (window KV is read back from the pool at its global
    flat index, exactly like chunked prefill).  The host guarantees
    table coverage of positions < pos_limit through lengths + T.

    Gather path only: the pallas paged-attention kernel is single-query
    decode, and T here is the small speculative window (k+1 <= ~8) — the
    gather's overhead is one chunk-sized span read, the same trade
    chunked prefill already makes.  Returns (logits [B, T, V] fp32,
    updated pool).
    """
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    b, t = tokens.shape
    bs = pool["k"].shape[2]
    w = table.shape[1]
    cdt = cfg.compute_dtype
    limit = pos_limit if pos_limit is not None else w * bs
    positions = lengths[:, None] + jnp.arange(t)[None, :]  # [B, T] global
    ok = positions < limit
    safe = jnp.minimum(positions, limit - 1)  # rope-table safe
    bidx = jnp.arange(b)[:, None]
    blk = jnp.where(ok, table[bidx, safe // bs], 0)  # invalid -> sink
    off = safe % bs
    # flat span index == global position (the table row is the sequence's
    # blocks in order); window token j sees prefix + window tokens <= j
    span_mask = (jnp.arange(w * bs)[None, None, :]
                 <= positions[:, :, None])  # [B, T, W*bs] causal
    x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)
    overlap = tp_plan is not None and tp_plan.overlap

    def body(carry, inp):
        if overlap:
            x, pk_all, pv_all, tok = carry
        else:
            (x, pk_all, pv_all), tok = carry, None
        lp, li = inp
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"].astype(cdt)).reshape(b, t, cfg.n_heads,
                                               cfg.head_dim)
        k = (h @ lp["wk"].astype(cdt)).reshape(b, t, cfg.n_kv_heads,
                                               cfg.head_dim)
        v = (h @ lp["wv"].astype(cdt)).reshape(b, t, cfg.n_kv_heads,
                                               cfg.head_dim)
        q = apply_rope(q, cos, sin, positions=safe)
        k = apply_rope(k, cos, sin, positions=safe)
        # [B, T] fancy-index scatter; duplicate sink indices collide with
        # garbage values only (no slot's table references block 0 inside
        # its live span)
        pk_all = pk_all.at[li, blk, off].set(
            k.reshape(b, t, -1).astype(pk_all.dtype))
        pv_all = pv_all.at[li, blk, off].set(
            v.reshape(b, t, -1).astype(pv_all.dtype))
        ck = pk_all[li, table].reshape(b, w * bs, cfg.n_kv_heads,
                                       cfg.head_dim)
        cv = pv_all[li, table].reshape(b, w * bs, cfg.n_kv_heads,
                                       cfg.head_dim)
        attn = _paged_attend(cfg, q, ck, cv, span_mask)
        out, tok = _tp_out_proj(attn.astype(cdt), lp["wo"].astype(cdt),
                                tp_plan, tok)
        x = x + out
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        gated = (jax.nn.silu(h @ lp["w_gate"].astype(cdt))
                 * (h @ lp["w_up"].astype(cdt)))
        ffn, tok = _tp_out_proj(gated, lp["w_down"].astype(cdt),
                                tp_plan, tok)
        carry = (x + ffn, pk_all, pv_all)
        return (carry + (tok,) if overlap else carry), None

    carry0 = (x, pool["k"], pool["v"])
    if overlap:
        carry0 = carry0 + (jnp.zeros((), cfg.compute_dtype),)
    carry, _ = lax.scan(
        body, carry0, (params["layers"], jnp.arange(cfg.n_layers)))
    x, ks, vs = carry[:3]
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cdt)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def prefill_chunk_paged(cfg: LlamaConfig, params: Params, tokens: jnp.ndarray,
                        pool: Dict[str, jnp.ndarray], table: jnp.ndarray,
                        p0: jnp.ndarray,
                        rope_cache: Optional[tuple] = None,
                        tp_plan: Optional[TPPlan] = None):
    """Prefill ONE chunk of a single sequence into its pool blocks.

    tokens [1, C] (C a multiple of block_size; tail garbage-padded — padded
    positions write blocks the sequence owns and are masked by length
    thereafter); p0 = global position of tokens[0, 0] (multiple of
    block_size); table [1, W] covers positions [0, p0 + C).  Attention is
    causal over the whole prefix: earlier chunks' KV is read back from the
    pool, so chunked prefill needs no growing-activation state between
    chunks (chunk compute is O(C * (p0 + C))).
    Returns (logits [1, C, V] fp32, updated pool).
    """
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    b, c = tokens.shape
    bs = pool["k"].shape[2]
    w = table.shape[1]
    cdt = cfg.compute_dtype
    positions = p0 + jnp.arange(c)  # [C] global positions
    # the C/bs physical blocks this chunk writes
    chunk_blocks = lax.dynamic_slice(table[0], (p0 // bs,), (c // bs,))
    span_mask = (jnp.arange(w * bs)[None, None, :]
                 <= positions[None, :, None])  # [1, C, W*bs] causal
    x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)
    overlap = tp_plan is not None and tp_plan.overlap

    def body(carry, inp):
        # pools [L, NB, bs, kv*hd] ride the carry
        if overlap:
            x, pk_all, pv_all, tok = carry
        else:
            (x, pk_all, pv_all), tok = carry, None
        lp, li = inp
        h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q = (h @ lp["wq"].astype(cdt)).reshape(b, c, cfg.n_heads, cfg.head_dim)
        k = (h @ lp["wk"].astype(cdt)).reshape(b, c, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ lp["wv"].astype(cdt)).reshape(b, c, cfg.n_kv_heads, cfg.head_dim)
        q = apply_rope(q, cos, sin, positions=positions[None, :])
        k = apply_rope(k, cos, sin, positions=positions[None, :])
        # [1, C, kv, hd] -> [C/bs, bs, kv*hd] block-major slab writes
        pk_all = pk_all.at[li, chunk_blocks].set(
            k[0].reshape(c // bs, bs, -1).astype(pk_all.dtype))
        pv_all = pv_all.at[li, chunk_blocks].set(
            v[0].reshape(c // bs, bs, -1).astype(pv_all.dtype))
        ck = pk_all[li, table].reshape(b, w * bs, cfg.n_kv_heads, cfg.head_dim)
        cv = pv_all[li, table].reshape(b, w * bs, cfg.n_kv_heads, cfg.head_dim)
        attn = _paged_attend(cfg, q, ck, cv, span_mask)
        out, tok = _tp_out_proj(attn.astype(cdt), lp["wo"].astype(cdt),
                                tp_plan, tok)
        x = x + out
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        gated = (jax.nn.silu(h @ lp["w_gate"].astype(cdt))
                 * (h @ lp["w_up"].astype(cdt)))
        ffn, tok = _tp_out_proj(gated, lp["w_down"].astype(cdt),
                                tp_plan, tok)
        carry = (x + ffn, pk_all, pv_all)
        return (carry + (tok,) if overlap else carry), None

    carry0 = (x, pool["k"], pool["v"])
    if overlap:
        carry0 = carry0 + (jnp.zeros((), cfg.compute_dtype),)
    carry, _ = lax.scan(
        body, carry0, (params["layers"], jnp.arange(cfg.n_layers)))
    x, ks, vs = carry[:3]
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(cdt)).astype(jnp.float32)
    return logits, {"k": ks, "v": vs}


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (6N + attention term) for MFU math."""
    n = cfg.num_params
    attn = 12 * cfg.n_layers * cfg.dim * seq_len  # 2*2*3 * L * d * s (fwd+bwd, causal half)
    return 6.0 * n + attn
