"""Mixtral-style sparse mixture-of-experts decoder LM, TPU-first.

The reference delegates MoE models to engines (vLLM for serving, torch for
training — SURVEY.md §2.3 Ray LLM); here MoE is a first-class model family
built the TPU way:

  - **expert parallelism as a mesh axis**: expert weights are sharded over
    the canonical "expert" axis; token dispatch/combine are einsums against
    a capacity-bounded dispatch mask, so XLA lowers routing to all-to-alls
    over ICI (GShard/Switch formulation — compiler-friendly, no scatter
    loops, static shapes).
  - attention/norm/rope reuse ray_tpu.ops (pallas flash kernel on TPU).
  - top-k routing with renormalised softmax weights + Switch-style
    load-balancing auxiliary loss.
  - layers stacked and scanned with per-layer remat, like models/llama.py.

Activations' batch dims are sharded over (data, fsdp, expert) — the expert
axis doubles as extra data parallelism outside the MoE block, the standard
TPU MoE layout.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import multi_head_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies

Params = Dict[str, Any]

# MoE activations use the expert axis as extra data parallelism.
MOE_BATCH_AXES = ("data", "fsdp", "expert")
ACTIVATION_BATCH_AXES = MOE_BATCH_AXES


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    # "auto": sorted/ragged grouped matmul when unsharded (the single-chip
    # DROP-FREE path — no capacity padding, no O(T²) dispatch einsums),
    # GShard capacity-dense dispatch under a mesh (its einsum formulation
    # is what GSPMD lowers to expert all-to-alls).
    # "sorted_capacity": counting-sort dispatch + padded batched-matmul
    # FFN — the fastest single-chip path (measured 64% vs ragged_dot's 45%
    # MXU at bench shapes; see moe_block_sorted_capacity) at the standard
    # capacity_factor token-dropping tradeoff.
    # "ragged" / "dense" force one implementation.
    dispatch: str = "auto"

    def __post_init__(self):
        valid = ("auto", "ragged", "dense", "sorted_capacity")
        if self.dispatch not in valid:
            raise ValueError(
                f"dispatch={self.dispatch!r} — must be one of {valid}")
    max_seq_len: int = 8192
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True
    remat_policy: str = "full"  # "full" | "attn" | "dots" (see llama.py)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        d, f, v, e = self.dim, self.ffn_dim, self.vocab_size, self.n_experts
        hq = self.n_heads * self.head_dim
        hkv = self.n_kv_heads * self.head_dim
        per_layer = d * hq + 2 * d * hkv + hq * d + d * e + 3 * e * d * f + 2 * d
        return v * d + self.n_layers * per_layer + d + d * v

    @property
    def num_active_params(self) -> int:
        """Params touched per token (router picks k of E experts)."""
        d, f, v, k = self.dim, self.ffn_dim, self.vocab_size, self.experts_per_token
        hq = self.n_heads * self.head_dim
        hkv = self.n_kv_heads * self.head_dim
        per_layer = d * hq + 2 * d * hkv + hq * d + d * self.n_experts + 3 * k * d * f + 2 * d
        return v * d + self.n_layers * per_layer + d + d * v

    # ---- presets ----
    @classmethod
    def mixtral_8x7b(cls, **kw) -> "MoEConfig":
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw) -> "MoEConfig":
        kw.setdefault("vocab_size", 256)
        kw.setdefault("dim", 64)
        kw.setdefault("n_layers", 2)
        kw.setdefault("n_heads", 4)
        kw.setdefault("n_kv_heads", 2)
        kw.setdefault("ffn_dim", 128)
        kw.setdefault("n_experts", 4)
        kw.setdefault("experts_per_token", 2)
        kw.setdefault("max_seq_len", 128)
        kw.setdefault("compute_dtype", jnp.float32)
        return cls(**kw)


def init_params(cfg: MoEConfig, key: jax.Array) -> Params:
    d, f, e = cfg.dim, cfg.ffn_dim, cfg.n_experts
    hq = cfg.n_heads * cfg.head_dim
    hkv = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    std = 0.02
    out_std = std / math.sqrt(2 * L)
    ks = jax.random.split(key, 12)
    dt = cfg.param_dtype

    def norm_(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dt)

    return {
        "embed": norm_(ks[0], (cfg.vocab_size, d), std),
        "layers": {
            "attn_norm": jnp.ones((L, d), dt),
            "wq": norm_(ks[1], (L, d, hq), std),
            "wk": norm_(ks[2], (L, d, hkv), std),
            "wv": norm_(ks[3], (L, d, hkv), std),
            "wo": norm_(ks[4], (L, hq, d), out_std),
            "mlp_norm": jnp.ones((L, d), dt),
            # router stays fp32: tiny, and routing decisions are precision-
            # sensitive
            "router": jax.random.normal(ks[5], (L, d, e), jnp.float32) * std,
            "w_gate": norm_(ks[6], (L, e, d, f), std),
            "w_up": norm_(ks[7], (L, e, d, f), std),
            "w_down": norm_(ks[8], (L, e, f, d), out_std),
        },
        "final_norm": jnp.ones((d,), dt),
        "lm_head": norm_(ks[9], (d, cfg.vocab_size), std),
    }


def param_specs(cfg: MoEConfig) -> Params:
    """PartitionSpec tree: experts over "expert", TP over "tensor",
    fsdp on the remaining large dim."""
    return {
        "embed": P("tensor", "fsdp"),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, "fsdp", "tensor"),
            "wk": P(None, "fsdp", "tensor"),
            "wv": P(None, "fsdp", "tensor"),
            "wo": P(None, "tensor", "fsdp"),
            "mlp_norm": P(None, None),
            "router": P(None, None, None),
            "w_gate": P(None, "expert", "fsdp", "tensor"),
            "w_up": P(None, "expert", "fsdp", "tensor"),
            "w_down": P(None, "expert", "tensor", "fsdp"),
        },
        "final_norm": P(None),
        "lm_head": P("fsdp", "tensor"),
    }


def _constraint(x, spec, mesh):
    if mesh is None:
        return x
    return lax.with_sharding_constraint(x, jax.sharding.NamedSharding(mesh, spec))


def _router(cfg: MoEConfig, xt, lp):
    """Shared routing head: top-k expert ids + renormalised weights + the
    Switch load-balance aux loss. xt: [T, d]."""
    e, k = cfg.n_experts, cfg.experts_per_token
    logits = xt.astype(jnp.float32) @ lp["router"]        # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_w, top_idx = lax.top_k(probs, k)                   # [T, k]
    top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)
    # aux load-balance loss (Switch): E * sum_e frac_routed_e * mean_prob_e
    frac_routed = jnp.mean(jax.nn.one_hot(top_idx[:, 0], e, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_routed * mean_prob)
    return top_w, top_idx, aux


#: megablox row-tile: the support gate and the tiling tuple must agree
#: (megablox hard-errors when m % tile_m != 0)
_GMM_TILE_M = 512


def _gmm_supported(cfg: MoEConfig, n_rows: int, mesh) -> bool:
    """Whether the pallas megablox grouped-matmul kernel applies: TPU
    backend, UNSHARDED (a pallas custom call has no GSPMD partitioning
    rule — under a mesh the partitionable lax.ragged_dot HLO must stay),
    lane-aligned dims, and row count divisible by the m-tile."""
    if mesh is not None or jax.default_backend() != "tpu":
        return False
    if cfg.dim % 128 or cfg.ffn_dim % 128 or n_rows % _GMM_TILE_M:
        return False
    try:
        from jax.experimental.pallas.ops.tpu.megablox.ops import gmm  # noqa: F401
    except ImportError:
        return False
    return True


def _grouped_matmul(cfg: MoEConfig, use_gmm: bool, a, b, group_sizes):
    """One grouped matmul over expert-contiguous rows: the pallas megablox
    kernel where supported (measured v5e, 3-matmul FFN chain fwd+bwd at
    T*k=64k/E=8/d=2048/f=4096: 69.4% MXU with tiling (512,512,2048) vs
    40.8% through lax.ragged_dot — the round-4 ceiling VERDICT item 3
    asked to break; sweep in benchmarks/moe_gmm_ablate.py), else
    lax.ragged_dot.  The megablox wrapper ships a custom VJP, so the
    training path differentiates through it."""
    if use_gmm:
        from jax.experimental.pallas.ops.tpu.megablox.ops import gmm

        # tiling swept on v5e over the FFN fwd+bwd chain: (512,512,2048)
        # 69.4% MXU vs (512,1024,1024) 60.1%; larger tiles exceed VMEM at
        # compile (all figures reproduced by benchmarks/moe_gmm_ablate.py)
        k_dim, n_dim = b.shape[1], b.shape[2]
        tiling = (_GMM_TILE_M, min(512, k_dim), min(2048, n_dim))
        return gmm(a, b, group_sizes, a.dtype, tiling)
    return lax.ragged_dot(a, b, group_sizes)


def moe_block_ragged(cfg: MoEConfig, x, lp, mesh=None):
    """Sorted/ragged top-k MoE FFN (megablox-style grouped matmul).

    Token-expert pairs are sorted by expert, expert FFNs run as ONE
    grouped matmul per projection over the contiguous groups (pallas
    megablox kernel on TPU, lax.ragged_dot elsewhere), and results
    scatter-add back. Exactly 3*2*T*k*d*f matmul FLOPs:
    no [T, E, cap] dispatch/combine einsums (O(T²·d) at scale — the reason
    the dense path measured 0.26 active-MFU), no capacity padding, and no
    token dropping. x: [B, S, d] -> ([B, S, d], aux_loss scalar).
    """
    b, s, d = x.shape
    cdt = cfg.compute_dtype
    k = cfg.experts_per_token
    t = b * s

    xt = x.reshape(t, d)
    top_w, top_idx, aux = _router(cfg, xt, lp)

    # group token-expert pairs by expert with a COUNTING sort: expert ids
    # live in [0, E), so a cumsum of one-hots gives each pair's rank within
    # its expert in O(N·E) vector ops — the general argsort is a bitonic
    # O(N log²N) sort on TPU and showed up in step profiles
    n = t * k
    flat_e = top_idx.reshape(-1)                   # [N] expert assignment
    onehot = jax.nn.one_hot(flat_e, cfg.n_experts, dtype=jnp.int32)
    rank = ((jnp.cumsum(onehot, axis=0) - onehot) * onehot).sum(-1)  # [N]
    group_sizes = onehot.sum(0)                    # [E]
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(group_sizes)[:-1]])
    pos = rank + offsets[flat_e]                   # destination sorted slot
    # inverse permutation: sorted slot -> source pair (stable, like argsort)
    order = jnp.zeros((n,), jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32))
    tok = order // k                               # source token per sorted slot
    sx = jnp.take(xt, tok, axis=0).astype(cdt)     # [N, d] gather

    use_gmm = _gmm_supported(cfg, n, mesh)
    gate = _grouped_matmul(cfg, use_gmm, sx, lp["w_gate"].astype(cdt),
                           group_sizes)
    up = _grouped_matmul(cfg, use_gmm, sx, lp["w_up"].astype(cdt),
                         group_sizes)
    act = jax.nn.silu(gate) * up
    out = _grouped_matmul(cfg, use_gmm, act, lp["w_down"].astype(cdt),
                          group_sizes)  # [T*k, d]

    w_sorted = top_w.reshape(-1)[order].astype(out.dtype)
    y = jnp.zeros((t, d), out.dtype).at[tok].add(out * w_sorted[:, None])
    return y.reshape(b, s, d), aux


def moe_block_sorted_capacity(cfg: MoEConfig, x, lp):
    """Counting-sort dispatch + PADDED batched-matmul expert FFN.

    Measured on v5e (round 4): at the bench shapes (T*k=64k rows over 8
    experts of d=2048/f=4096) the 3-matmul FFN runs 64.2% MXU as a batched
    einsum over equal [E, cap, d] groups vs 44.6% through lax.ragged_dot —
    the ragged kernel, not routing or dispatch, is the exact path's MFU
    ceiling.  This path buys the batched kernel with the STANDARD capacity
    tradeoff (GShard/Switch): pairs ranked past ``capacity_factor * T*k/E``
    within their expert are dropped (contribute zero).  Dispatch stays the
    O(N·E) counting sort + index scatter/gather — none of the [T, E, cap]
    one-hot einsums that sank the dense path to 0.26 MFU.
    x: [B, S, d] -> ([B, S, d], aux_loss scalar).
    """
    b, s, d = x.shape
    cdt = cfg.compute_dtype
    e, k = cfg.n_experts, cfg.experts_per_token
    t = b * s
    n = t * k
    cap = int(math.ceil(cfg.capacity_factor * n / e))
    cap = min(t, ((cap + 127) // 128) * 128)  # MXU-tile multiple

    xt = x.reshape(t, d)
    top_w, top_idx, aux = _router(cfg, xt, lp)

    flat_e = top_idx.reshape(-1)                           # [N]
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)
    rank = ((jnp.cumsum(onehot, axis=0) - onehot) * onehot).sum(-1)  # [N]
    keep = rank < cap
    trash = e * cap                                        # overflow row
    dst = jnp.where(keep, flat_e * cap + rank, trash)      # [N] unique slots
    pair_tok = jnp.arange(n, dtype=jnp.int32) // k
    sx = jnp.take(xt, pair_tok, axis=0).astype(cdt)        # [N, d]
    buf = jnp.zeros((e * cap + 1, d), cdt).at[dst].set(sx)
    xg = buf[:e * cap].reshape(e, cap, d)

    gate = jnp.einsum("ecd,edf->ecf", xg, lp["w_gate"].astype(cdt))
    up = jnp.einsum("ecd,edf->ecf", xg, lp["w_up"].astype(cdt))
    out = jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up,
                     lp["w_down"].astype(cdt))

    # fill-mode gather: overflow slots (dst == e*cap) read zeros without a
    # concatenate copy of the [E*cap, d] output
    pair_out = out.reshape(e * cap, d).at[dst].get(
        mode="fill", fill_value=0)
    w_pair = (top_w.reshape(-1) * keep).astype(pair_out.dtype)
    y = jnp.zeros((t, d), pair_out.dtype).at[pair_tok].add(
        pair_out * w_pair[:, None])
    return y.reshape(b, s, d), aux


def moe_block(cfg: MoEConfig, x, lp, mesh):
    """Capacity-bounded top-k MoE FFN (GShard-style dense dispatch).

    x: [B, S, d] -> ([B, S, d], aux_loss scalar)

    NOTE under dispatch="auto" the model math is topology-dependent: the
    unsharded path routes EVERY token (ragged, no capacity), the meshed
    path drops tokens past the capacity bound — so a single-chip run is
    not a bitwise repro of a meshed run. Force dispatch="dense" when
    reproducing meshed numerics on one chip (see MoEConfig.dispatch).
    """
    if cfg.dispatch == "sorted_capacity":
        return moe_block_sorted_capacity(cfg, x, lp)
    if cfg.dispatch == "ragged" or (cfg.dispatch == "auto" and mesh is None):
        return moe_block_ragged(cfg, x, lp, mesh)
    b, s, d = x.shape
    cdt = cfg.compute_dtype
    e, k = cfg.n_experts, cfg.experts_per_token
    t = b * s
    cap = int(math.ceil(cfg.capacity_factor * k * t / e))
    cap = min(cap, t)

    xt = x.reshape(t, d)
    top_w, top_idx, aux = _router(cfg, xt, lp)

    # dispatch/combine tensors [T, E, cap] via one-hot + per-expert cumsum
    dispatch = jnp.zeros((t, e, cap), jnp.bool_)
    combine = jnp.zeros((t, e, cap), jnp.float32)
    # priority: k=0 choices fill expert slots first (matches GShard)
    position_base = jnp.zeros((e,), jnp.int32)
    for ki in range(k):
        onehot = jax.nn.one_hot(top_idx[:, ki], e, dtype=jnp.int32)   # [T, E]
        pos = jnp.cumsum(onehot, axis=0) - 1 + position_base[None, :]  # [T, E]
        position_base = position_base + onehot.sum(0)
        keep = (pos < cap) & (onehot > 0)
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos, cap), cap + 1,
                                dtype=jnp.bool_)[..., :cap]            # [T,E,cap]
        dispatch = dispatch | pos_oh
        combine = combine + pos_oh.astype(jnp.float32) * top_w[:, ki, None, None]

    # route -> expert compute -> unroute; XLA inserts all-to-alls across the
    # "expert" axis (tokens sharded on T, experts sharded on E)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cdt), xt.astype(cdt))
    expert_in = _constraint(expert_in, P("expert", None, None), mesh)
    gate = jnp.einsum("ecd,edf->ecf", expert_in, lp["w_gate"].astype(cdt))
    up = jnp.einsum("ecd,edf->ecf", expert_in, lp["w_up"].astype(cdt))
    act = jax.nn.silu(gate) * up
    act = _constraint(act, P("expert", None, "tensor"), mesh)
    out = jnp.einsum("ecf,efd->ecd", act, lp["w_down"].astype(cdt))
    out = _constraint(out, P("expert", None, None), mesh)
    y = jnp.einsum("tec,ecd->td", combine.astype(cdt), out.astype(cdt))
    return y.reshape(b, s, d), aux


def _layer(cfg: MoEConfig, carry, lp, cos, sin, mesh):
    x, aux_acc = carry
    b, s, d = x.shape
    cdt = cfg.compute_dtype

    h = rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
    q = (h @ lp["wq"].astype(cdt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    kk = (h @ lp["wk"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ lp["wv"].astype(cdt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = _constraint(q, P(MOE_BATCH_AXES, None, "tensor", None), mesh)
    kk = _constraint(kk, P(MOE_BATCH_AXES, None, "tensor", None), mesh)
    q = apply_rope(q, cos[:s], sin[:s])
    kk = apply_rope(kk, cos[:s], sin[:s])
    attn = multi_head_attention(q, kk, v, causal=True)
    attn = attn.reshape(b, s, cfg.n_heads * cfg.head_dim)
    attn = checkpoint_name(attn, "attn_out")
    x = x + (attn @ lp["wo"].astype(cdt))
    x = _constraint(x, P(MOE_BATCH_AXES, None, None), mesh)

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
    ffn, aux = moe_block(cfg, h, lp, mesh)
    x = x + ffn
    x = _constraint(x, P(MOE_BATCH_AXES, None, None), mesh)
    return (x, aux_acc + aux)


def forward(
    cfg: MoEConfig,
    params: Params,
    tokens: jnp.ndarray,
    *,
    mesh: Optional[Mesh] = None,
    context_parallel: bool = False,  # parity with llama.forward signature
    rope_cache: Optional[tuple] = None,
):
    """Token ids [B, S] -> (logits [B, S, V] fp32, aux_loss scalar)."""
    del context_parallel  # MoE + CP composition lands with the CP rewrite
    if rope_cache is None:
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    else:
        cos, sin = rope_cache
    # The embed dim of the table must not stay "fsdp"-sharded through the
    # token gather: the gather output would inherit that sharding on its
    # last dim and the reshard to batch sharding forces the SPMD partitioner
    # into an involuntary full rematerialization (replicate-then-slice) in
    # fwd AND bwd. Keep the vocab dim TP-sharded (XLA partitions the gather
    # with a masked psum) but all-gather the embed dim over fsdp explicitly.
    emb = _constraint(params["embed"], P("tensor", None), mesh)
    x = jnp.take(emb, tokens, axis=0).astype(cfg.compute_dtype)
    x = _constraint(x, P(MOE_BATCH_AXES, None, None), mesh)

    from ray_tpu.models.llama import _remat_policy

    layer = partial(_layer, cfg, cos=cos, sin=sin, mesh=mesh)
    if cfg.remat:
        layer = jax.checkpoint(layer, policy=_remat_policy(cfg))

    def body(carry, lp):
        return layer(carry, lp), None

    (x, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.compute_dtype)).astype(jnp.float32)
    logits = _constraint(logits, P(MOE_BATCH_AXES, None, "tensor"), mesh)
    return logits, aux / cfg.n_layers


def loss_fn(
    cfg: MoEConfig,
    params: Params,
    tokens: jnp.ndarray,
    *,
    loss_mask: Optional[jnp.ndarray] = None,
    mesh: Optional[Mesh] = None,
    context_parallel: bool = False,
    rope_cache: Optional[tuple] = None,
) -> jnp.ndarray:
    """Next-token cross-entropy + load-balancing aux term."""
    logits, aux = forward(
        cfg, params, tokens, mesh=mesh, context_parallel=context_parallel,
        rope_cache=rope_cache,
    )
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt_logit = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - tgt_logit
    if loss_mask is not None:
        m = loss_mask[:, 1:].astype(nll.dtype)
        ce = jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
    else:
        ce = jnp.mean(nll)
    return ce + cfg.aux_loss_coef * aux


def flops_per_token(cfg: MoEConfig, seq_len: int) -> float:
    """Training FLOPs/token based on *active* params (what MFU measures)."""
    n = cfg.num_active_params
    attn = 12 * cfg.n_layers * cfg.dim * seq_len
    return 6.0 * n + attn
