"""Model families shipped with the framework.

The reference framework ships models indirectly (vLLM engines, RLlib
modules); here the flagship decoder-LM family is native jax so the trainer,
benchmark, and serving paths share one sharding-aware implementation.
"""

from ray_tpu.models.llama import LlamaConfig, forward, init_params, loss_fn, param_specs
from ray_tpu.models.moe import MoEConfig

__all__ = ["LlamaConfig", "MoEConfig", "forward", "init_params", "loss_fn", "param_specs"]
