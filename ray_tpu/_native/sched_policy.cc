// Hybrid scheduling policy scorer — the hot node-selection inner loop.
//
// reference: src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:29-49
// (top-k selection by utilization score, local-first) and
// cluster_resource_scheduler.h:99 GetBestSchedulableNode.  The Python
// ClusterResourceScheduler prepares per-node flags (feasible / can-allocate /
// utilization) and delegates the selection to this scorer; at thousands of
// nodes the sort+select dominates lease latency, which is why the reference
// keeps it native.
//
// Build: handled by ray_tpu._native.load("sched_policy") (g++ -O2 -shared).

#include <algorithm>
#include <cstdint>
#include <random>
#include <vector>

extern "C" {

// Returns the chosen node index in [0, n), or -1 if no candidate.
//   can_alloc[i]  — node i can run the demand right now
//   feasible[i]   — node i could run it when resources free (superset)
//   utilization[i]— node i's max-resource utilization in [0, 1]
//   prefer_idx    — local node (-1 none): taken immediately if can_alloc
//   top_k_abs / top_k_frac — k = max(abs, frac * pool_size), min 1
//   seed          — RNG seed for the top-k pick (deterministic for tests)
long long hybrid_choose(const unsigned char* feasible,
                        const unsigned char* can_alloc,
                        const double* utilization,
                        long long n,
                        long long prefer_idx,
                        long long top_k_abs,
                        double top_k_frac,
                        unsigned long long seed) {
  if (n <= 0) return -1;
  if (prefer_idx >= 0 && prefer_idx < n && can_alloc[prefer_idx] &&
      feasible[prefer_idx]) {
    return prefer_idx;
  }
  std::vector<long long> pool;
  pool.reserve(n);
  for (long long i = 0; i < n; ++i) {
    if (feasible[i] && can_alloc[i]) pool.push_back(i);
  }
  if (pool.empty()) {  // queue on a feasible node if none is free
    for (long long i = 0; i < n; ++i) {
      if (feasible[i]) pool.push_back(i);
    }
  }
  if (pool.empty()) return -1;
  std::sort(pool.begin(), pool.end(), [&](long long a, long long b) {
    if (utilization[a] != utilization[b]) return utilization[a] < utilization[b];
    return a < b;
  });
  long long k = std::max<long long>(
      top_k_abs, static_cast<long long>(pool.size() * top_k_frac));
  k = std::max<long long>(1, std::min<long long>(k, pool.size()));
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<long long> dist(0, k - 1);
  return pool[dist(rng)];
}

}  // extern "C"
