"""Native (C++) components, built on demand with g++ and loaded via ctypes.

The build is cached next to the source (``.so`` beside the ``.cc``); a failed
toolchain falls back to the pure-Python implementations, so the package works
everywhere and is merely faster where a compiler exists.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_build_lock = threading.Lock()
_cache: dict = {}


def _sanitize_mode() -> str | None:
    """RAY_TPU_NATIVE_SANITIZE selects an instrumented build/load variant:
    "1"/"address" -> ASAN (lib<name>.asan.so), "thread" -> TSAN
    (lib<name>.tsan.so). The process must run with the matching runtime
    preloaded (LD_PRELOAD) — tests/test_native_asan.py and
    tests/test_native_tsan.py drive the native suite both ways.
    reference: the reference CI's .bazelrc asan/tsan configs
    (.bazelrc:114-134 in the upstream repo)."""
    v = os.environ.get("RAY_TPU_NATIVE_SANITIZE")
    if v in ("1", "address"):
        return "address"
    if v == "thread":
        return "thread"
    return None


def _build(name: str, extra_flags=()) -> str | None:
    src = os.path.join(_DIR, f"{name}.cc")
    mode = _sanitize_mode()
    if mode == "address":
        out = os.path.join(_DIR, f"lib{name}.asan.so")
        flags = ["-O1", "-g", "-fno-omit-frame-pointer", "-fsanitize=address",
                 *extra_flags]
    elif mode == "thread":
        out = os.path.join(_DIR, f"lib{name}.tsan.so")
        flags = ["-O1", "-g", "-fno-omit-frame-pointer", "-fsanitize=thread",
                 *extra_flags]
    else:
        out = os.path.join(_DIR, f"lib{name}.so")
        flags = ["-O2", *extra_flags]
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-std=c++17", "-fPIC", "-shared", "-o", out, src,
           "-lrt", *flags]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        stderr = getattr(e, "stderr", b"")
        logger.warning("native build of %s failed (%s); using Python fallback",
                       name, (stderr or b"").decode(errors="replace")[:500])
        return None


def load(name: str) -> ctypes.CDLL | None:
    """Build (if needed) and dlopen a native component; None on failure."""
    with _build_lock:
        if name in _cache:
            return _cache[name]
        # graftlint: allow(blocking-under-lock) — the lock EXISTS to
        # single-flight the g++ compile; waiters need its artifact and
        # cannot proceed until it lands in _cache
        path = _build(name)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError as e:
                logger.warning("dlopen %s failed: %s", path, e)
        _cache[name] = lib
        return lib


def load_sched_policy() -> ctypes.CDLL | None:
    lib = load("sched_policy")
    if lib is None:
        return None
    lib.hybrid_choose.restype = ctypes.c_longlong
    lib.hybrid_choose.argtypes = [
        ctypes.POINTER(ctypes.c_ubyte), ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_longlong, ctypes.c_double,
        ctypes.c_ulonglong,
    ]
    return lib


def load_plasma() -> ctypes.CDLL | None:
    lib = load("plasma_store")
    if lib is None:
        return None
    lib.plasma_create.restype = ctypes.c_void_p
    lib.plasma_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.plasma_destroy.argtypes = [ctypes.c_void_p]
    lib.plasma_alloc.restype = ctypes.c_uint64
    lib.plasma_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    for fn in ("plasma_seal", "plasma_unpin", "plasma_contains",
               "plasma_mark_secondary", "plasma_free"):
        getattr(lib, fn).restype = ctypes.c_int
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.plasma_get.restype = ctypes.c_int
    lib.plasma_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.POINTER(ctypes.c_uint64)]
    lib.plasma_evict.restype = ctypes.c_int
    lib.plasma_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
                                 ctypes.c_char_p, ctypes.c_uint64]
    for fn in ("plasma_used", "plasma_capacity", "plasma_num_objects"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.plasma_base.restype = ctypes.c_void_p
    lib.plasma_base.argtypes = [ctypes.c_void_p]
    return lib
