// Native stack dumps of live workers (reference capability:
// dashboard/modules/reporter/reporter_agent.py shells out to py-spy for
// stacks of ANY worker, including ones wedged inside C++/CUDA; here the
// worker carries its own dumper).
//
// stack_dump_install(path) registers a C-LEVEL SIGUSR2 handler that
// writes the RECEIVING thread's native backtrace to `path`.  A Python
// signal handler only runs between bytecodes — a thread stuck inside an
// XLA dispatch or a native arena never reaches one; a C handler
// interrupts blocking C code directly.  The raylet's dump endpoint
// directs the signal at every thread (tgkill), so each thread appends
// its own frames.
//
// Async-signal-safety: backtrace(3)/backtrace_symbols_fd(3) are the
// sanctioned not-quite-safe workhorses of every production crash
// reporter (the first backtrace call is made at install time so libgcc's
// unwinder state is initialized before any signal arrives).

#include <execinfo.h>
#include <fcntl.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/syscall.h>
#include <unistd.h>

static int g_fd = -1;

static void handler(int sig, siginfo_t* info, void* ctx) {
  (void)sig;
  (void)info;
  (void)ctx;
  if (g_fd < 0) return;
  void* buf[64];
  int n = backtrace(buf, 64);
  char head[96];
  long tid = (long)syscall(SYS_gettid);
  int len = snprintf(head, sizeof(head), "=== native stack tid %ld ===\n", tid);
  if (len > 0) {
    ssize_t r = write(g_fd, head, (size_t)len);
    (void)r;
  }
  backtrace_symbols_fd(buf, n, g_fd);
  static const char kEnd[] = "=== end ===\n";
  ssize_t r = write(g_fd, kEnd, sizeof(kEnd) - 1);
  (void)r;
}

extern "C" int stack_dump_install(const char* path) {
  // pre-initialize the unwinder outside signal context
  void* warm[4];
  backtrace(warm, 4);
  int fd = open(path, O_WRONLY | O_CREAT | O_APPEND | O_NOFOLLOW | O_CLOEXEC,
                0600);
  if (fd < 0) return -1;
  g_fd = fd;
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = handler;
  sa.sa_flags = SA_SIGINFO | SA_RESTART;  // wedged syscalls resume, unharmed
  sigemptyset(&sa.sa_mask);
  if (sigaction(SIGUSR2, &sa, nullptr) != 0) return -2;
  return 0;
}
