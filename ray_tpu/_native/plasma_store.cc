// Shared-memory arena object store — the native core of the node-local
// object store ("plasma" equivalent).
//
// TPU-native rebuild of the reference's plasma store internals
// (reference: src/ray/object_manager/plasma/store.h:55 PlasmaStore,
// dlmalloc.cc arena allocation, eviction_policy.h LRU,
// obj_lifecycle_mgr.h object table). Design:
//
//   * ONE posix shm arena per node (vs. the Python fallback's
//     segment-per-object): clients mmap the arena once and read objects at
//     (offset, size) — zero-copy, one mmap per process for any object count.
//   * first-fit free-list allocator with neighbour coalescing (the role
//     dlmalloc plays in the reference).
//   * object table with seal state, pin counts, LRU clock, and an eviction
//     sweep (sealed+unpinned, oldest first).
//
// Exposed as a C ABI consumed via ctypes (this environment has no pybind11);
// the raylet holds the store handle, workers attach the arena by name.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kAlign = 64;  // cache-line align objects

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

struct Entry {
  uint64_t offset = 0;
  uint64_t size = 0;        // requested size
  uint64_t alloc_size = 0;  // aligned size actually reserved
  bool sealed = false;
  int pins = 0;
  uint64_t lru_clock = 0;
  bool is_primary = true;
};

class ArenaStore {
 public:
  ArenaStore(const char* shm_name, uint64_t capacity)
      : shm_name_(shm_name), capacity_(capacity) {
    fd_ = shm_open(shm_name, O_CREAT | O_RDWR, 0600);
    if (fd_ < 0) return;
    if (ftruncate(fd_, static_cast<off_t>(capacity)) != 0) {
      close(fd_);
      fd_ = -1;
      return;
    }
    base_ = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd_, 0);
    if (base_ == MAP_FAILED) {
      base_ = nullptr;
      close(fd_);
      fd_ = -1;
      return;
    }
    free_blocks_[0] = capacity;  // one big free block
  }

  ~ArenaStore() {
    if (base_) munmap(base_, capacity_);
    if (fd_ >= 0) {
      close(fd_);
      shm_unlink(shm_name_.c_str());
    }
  }

  bool ok() const { return base_ != nullptr; }

  // returns offset, or UINT64_MAX when no block fits (caller evicts+retries)
  uint64_t Alloc(const std::string& oid, uint64_t size) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = table_.find(oid);
    if (it != table_.end()) {
      return it->second.sealed ? UINT64_MAX - 1 : it->second.offset;
    }
    uint64_t need = align_up(std::max<uint64_t>(size, 1));
    // first fit
    for (auto fit = free_blocks_.begin(); fit != free_blocks_.end(); ++fit) {
      if (fit->second >= need) {
        uint64_t off = fit->first;
        uint64_t remaining = fit->second - need;
        free_blocks_.erase(fit);
        if (remaining > 0) free_blocks_[off + need] = remaining;
        Entry e;
        e.offset = off;
        e.size = size;
        e.alloc_size = need;
        e.lru_clock = ++clock_;
        table_[oid] = e;
        used_ += need;
        return off;
      }
    }
    return UINT64_MAX;
  }

  int Seal(const std::string& oid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = table_.find(oid);
    if (it == table_.end()) return -1;
    it->second.sealed = true;
    it->second.lru_clock = ++clock_;
    return 0;
  }

  // pins on success
  int Get(const std::string& oid, uint64_t* offset, uint64_t* size) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = table_.find(oid);
    if (it == table_.end() || !it->second.sealed) return -1;
    it->second.pins++;
    it->second.lru_clock = ++clock_;
    *offset = it->second.offset;
    *size = it->second.size;
    return 0;
  }

  int Unpin(const std::string& oid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = table_.find(oid);
    if (it == table_.end()) return -1;
    if (it->second.pins > 0) it->second.pins--;
    return 0;
  }

  int Contains(const std::string& oid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = table_.find(oid);
    return (it != table_.end() && it->second.sealed) ? 1 : 0;
  }

  int MarkSecondary(const std::string& oid) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = table_.find(oid);
    if (it == table_.end()) return -1;
    it->second.is_primary = false;
    return 0;
  }

  int Free(const std::string& oid) {
    std::lock_guard<std::mutex> g(mu_);
    return FreeLocked(oid);
  }

  // Evict sealed, unpinned objects (secondaries first, then LRU) until
  // `need` bytes could be allocated. Evicted ids are written as
  // newline-separated hex into out_buf (for the caller to drop metadata /
  // spill bookkeeping). Returns number evicted, or -1 if still not enough.
  int Evict(uint64_t need, int evict_primaries, char* out_buf, uint64_t buf_len) {
    std::lock_guard<std::mutex> g(mu_);
    need = align_up(std::max<uint64_t>(need, 1));
    uint64_t out_pos = 0;
    int evicted = 0;
    while (LargestFree() < need) {
      // pick victim: secondaries first, then oldest LRU primary
      const std::string* victim = nullptr;
      uint64_t best_clock = UINT64_MAX;
      bool best_primary = true;
      for (const auto& kv : table_) {
        const Entry& e = kv.second;
        if (!e.sealed || e.pins > 0) continue;
        if (e.is_primary && !evict_primaries) continue;
        // secondaries sort before primaries; then LRU
        if ((!e.is_primary && best_primary) ||
            ((e.is_primary == best_primary) && e.lru_clock < best_clock)) {
          victim = &kv.first;
          best_clock = e.lru_clock;
          best_primary = e.is_primary;
        }
      }
      if (!victim) return -1;
      std::string vid = *victim;
      if (out_buf && out_pos + vid.size() + 1 < buf_len) {
        memcpy(out_buf + out_pos, vid.data(), vid.size());
        out_pos += vid.size();
        out_buf[out_pos++] = '\n';
      }
      FreeLocked(vid);
      evicted++;
    }
    if (out_buf && out_pos < buf_len) out_buf[out_pos] = '\0';
    return evicted;
  }

  uint64_t Used() {
    std::lock_guard<std::mutex> g(mu_);
    return used_;
  }
  uint64_t Capacity() const { return capacity_; }
  uint64_t NumObjects() {
    std::lock_guard<std::mutex> g(mu_);
    return table_.size();
  }
  void* Base() const { return base_; }

 private:
  uint64_t LargestFree() const {
    uint64_t best = 0;
    for (const auto& kv : free_blocks_) best = std::max(best, kv.second);
    return best;
  }

  int FreeLocked(const std::string& oid) {
    auto it = table_.find(oid);
    if (it == table_.end()) return -1;
    uint64_t off = it->second.offset;
    uint64_t len = it->second.alloc_size;
    used_ -= len;
    table_.erase(it);
    // coalesce with neighbours
    auto next = free_blocks_.lower_bound(off);
    if (next != free_blocks_.begin()) {
      auto prev = std::prev(next);
      if (prev->first + prev->second == off) {
        off = prev->first;
        len += prev->second;
        free_blocks_.erase(prev);
      }
    }
    next = free_blocks_.lower_bound(off + len);
    if (next != free_blocks_.end() && next->first == off + len) {
      len += next->second;
      free_blocks_.erase(next);
    }
    free_blocks_[off] = len;
    return 0;
  }

  std::string shm_name_;
  uint64_t capacity_;
  int fd_ = -1;
  void* base_ = nullptr;
  std::mutex mu_;
  std::map<uint64_t, uint64_t> free_blocks_;  // offset -> size
  std::unordered_map<std::string, Entry> table_;
  uint64_t used_ = 0;
  uint64_t clock_ = 0;
};

}  // namespace

extern "C" {

void* plasma_create(const char* shm_name, uint64_t capacity) {
  auto* s = new ArenaStore(shm_name, capacity);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void plasma_destroy(void* store) { delete static_cast<ArenaStore*>(store); }

uint64_t plasma_alloc(void* store, const char* oid, uint64_t size) {
  return static_cast<ArenaStore*>(store)->Alloc(oid, size);
}

int plasma_seal(void* store, const char* oid) {
  return static_cast<ArenaStore*>(store)->Seal(oid);
}

int plasma_get(void* store, const char* oid, uint64_t* offset, uint64_t* size) {
  return static_cast<ArenaStore*>(store)->Get(oid, offset, size);
}

int plasma_unpin(void* store, const char* oid) {
  return static_cast<ArenaStore*>(store)->Unpin(oid);
}

int plasma_contains(void* store, const char* oid) {
  return static_cast<ArenaStore*>(store)->Contains(oid);
}

int plasma_mark_secondary(void* store, const char* oid) {
  return static_cast<ArenaStore*>(store)->MarkSecondary(oid);
}

int plasma_free(void* store, const char* oid) {
  return static_cast<ArenaStore*>(store)->Free(oid);
}

int plasma_evict(void* store, uint64_t need, int evict_primaries, char* out_buf,
                 uint64_t buf_len) {
  return static_cast<ArenaStore*>(store)->Evict(need, evict_primaries, out_buf,
                                                buf_len);
}

uint64_t plasma_used(void* store) { return static_cast<ArenaStore*>(store)->Used(); }

uint64_t plasma_capacity(void* store) {
  return static_cast<ArenaStore*>(store)->Capacity();
}

uint64_t plasma_num_objects(void* store) {
  return static_cast<ArenaStore*>(store)->NumObjects();
}

// raylet-process direct access (spill/restore IO without re-attaching)
void* plasma_base(void* store) { return static_cast<ArenaStore*>(store)->Base(); }

}  // extern "C"
