"""LoRA adapters for the Llama model family.

reference: python/ray/llm/ serves LoRA through the engine it delegates to
(vLLM multi-LoRA; adapters resolved per-request by model id and fetched
from ``dynamic_lora_loading_path``). TPU-native design:

  - an adapter is a pytree of (A [r, d_in], B [d_out, r]) pairs for the
    projection matrices of every layer (stacked on the layer axis like the
    base params, so the scan-over-layers structure is preserved);
  - serving merges adapters into the base weights (W' = W + scale * (B A)^T)
    — the engine's static-slot batched decode then runs UNCHANGED, which is
    the right TPU trade: per-slot adapter switching inside one jitted
    program would force gathers over adapter banks every step, while merged
    weights cost one einsum per load and nothing at decode time;
  - multi-adapter serving maps each adapter to a Serve multiplexed model id
    (reference: serve model multiplexing) so replicas cache merged params
    per adapter with LRU eviction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig

# base-params leaf names a LoRA adapter may target (layers subtree)
TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Sequence[str] = ("wq", "wv")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def init_lora(cfg: LlamaConfig, lora: LoRAConfig, key: jax.Array,
              dtype=jnp.float32) -> Dict[str, Any]:
    """A-matrices gaussian, B zero (adapters start as identity), stacked on
    the layer axis to match the base params' scan layout."""
    from ray_tpu.models import llama

    base_shapes = jax.eval_shape(
        lambda k: llama.init_params(cfg, k), jax.random.PRNGKey(0))
    out: Dict[str, Any] = {"layers": {}}
    keys = jax.random.split(key, len(lora.targets))
    for k, name in zip(keys, lora.targets):
        if name not in TARGETS:
            raise ValueError(f"unknown LoRA target {name!r}; choose from {TARGETS}")
        shape = base_shapes["layers"][name].shape  # [L, d_in, d_out]
        L, d_in, d_out = shape
        out["layers"][name] = {
            "A": jax.random.normal(k, (L, lora.rank, d_in), dtype) * 0.02,
            "B": jnp.zeros((L, d_out, lora.rank), dtype),
        }
    out["config"] = dataclasses.asdict(lora)
    return out


def merge_lora(params: Dict[str, Any], adapter: Dict[str, Any]) -> Dict[str, Any]:
    """Return params with W' = W + scale * (B A)^T per targeted projection.

    Functional (the base tree is shared, only targeted leaves are new), so
    N merged adapters cost N * (targeted-matrix) HBM, not N full models.
    """
    lcfg = LoRAConfig(**{k: v for k, v in adapter["config"].items()})
    new_layers = dict(params["layers"])
    for name, ab in adapter["layers"].items():
        w = params["layers"][name]
        # A: [L, r, d_in], B: [L, d_out, r] -> delta^T: [L, d_in, d_out]
        delta = jnp.einsum("lor,lri->lio", ab["B"], ab["A"]) * lcfg.scale
        new_layers[name] = (w + delta.astype(w.dtype))
    out = dict(params)
    out["layers"] = new_layers
    return out


def adapter_speculation(spec_cfg, model_id: Optional[str]):
    """Resolve speculative decoding for one multi-LoRA model id (the
    per-adapter draft choice, ``SpeculativeConfig.per_adapter``).

    Returns ``(effective_spec_cfg, draft_adapter)``:

      - ``(None, None)`` — speculation off for this adapter (no global
        spec config, or an explicit ``{"enabled": False}`` override);
      - ``(cfg, None)`` — the global config applies unchanged (possibly
        with a per-adapter ``num_speculative_tokens``);
      - ``(cfg, adapter)`` — additionally merge ``adapter`` (a LoRA tree
        targeting the DRAFT model) into the draft weights for this id,
        so a tuned target keeps its draft aligned (acceptance rate is a
        property of the model PAIR — serving a LoRA target against the
        base draft silently halves the speedup).
    """
    if spec_cfg is None:
        return None, None
    over = (spec_cfg.per_adapter or {}).get(model_id) if model_id else None
    if not over:
        return spec_cfg, None
    if not over.get("enabled", True):
        return None, None
    eff = spec_cfg
    k = over.get("num_speculative_tokens")
    if k is not None:
        if int(k) < 1:
            # an explicit 0 means "don't speculate for this adapter" —
            # swallowing it (falsy-zero) would silently keep the global k
            return None, None
        eff = dataclasses.replace(spec_cfg, num_speculative_tokens=int(k))
    return eff, over.get("draft_adapter")


def lora_param_specs(cfg: LlamaConfig, lora: LoRAConfig):
    """PartitionSpec tree for adapter params: rank dims replicated (tiny),
    model dims following the base layout so merges stay local."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.models import llama

    base = llama.param_specs(cfg)["layers"]
    out = {"layers": {}, "config": None}
    for name in lora.targets:
        bspec = base[name]  # P(None, in_axis, out_axis)
        out["layers"][name] = {
            "A": P(None, None, bspec[1]),
            "B": P(None, bspec[2], None),
        }
    return out


def trainable_mask(params: Dict[str, Any], adapter: Dict[str, Any]):
    """optax-style mask trees: (adapter_mask_true, base_mask_false) — for
    parameter-efficient finetuning, pair with optax.masked so only A/B
    update while the base stays frozen."""
    adapter_mask = jax.tree.map(lambda _: True, adapter)
    adapter_mask["config"] = False
    base_mask = jax.tree.map(lambda _: False, params)
    return adapter_mask, base_mask


class LoRAManager:
    """Adapter registry + merged-params LRU for a serving replica
    (reference: vLLM's LoRA cache behind ray.llm's model multiplexing)."""

    def __init__(self, base_params: Dict[str, Any], max_merged: int = 4):
        self._base = base_params
        self._adapters: Dict[str, Dict[str, Any]] = {}
        self._merged: Dict[str, Dict[str, Any]] = {}
        self._order: list = []
        self._max = max_merged

    def register(self, name: str, adapter: Dict[str, Any]):
        self._adapters[name] = adapter
        self._merged.pop(name, None)
        if name in self._order:
            self._order.remove(name)

    def adapter_names(self):
        return sorted(self._adapters)

    def params_for(self, name: Optional[str]) -> Dict[str, Any]:
        """Base params for None/unknown ids; merged params for adapters."""
        if not name or name not in self._adapters:
            return self._base
        cached = self._merged.get(name)
        if cached is not None:
            self._order.remove(name)
            self._order.append(name)
            return cached
        merged = merge_lora(self._base, self._adapters[name])
        self._merged[name] = merged
        self._order.append(name)
        while len(self._order) > self._max:
            evict = self._order.pop(0)
            self._merged.pop(evict, None)
        return merged
