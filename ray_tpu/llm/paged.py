"""Paged-KV LLM engine: block-table cache, chunked prefill, prefix caching.

The reference gets paged attention / chunked prefill / prefix caching by
delegating serving to vLLM (reference: llm/_internal/serve/deployments/llm/
vllm/vllm_models.py:177-186 passes engine_kwargs straight through); a
TPU-native rebuild provides the equivalent itself:

  - the KV cache is a POOL of fixed-size HBM blocks shared by every request
    (`models/llama.py init_paged_kv_cache`); a request's HBM cost is
    proportional to its ACTUAL length, not max_seq — admission is
    memory-based (free blocks), not slot-count
  - the device sees a padded block TABLE [B, W] per decode chunk, W bucketed
    to the max blocks any active slot uses: short batches read a SMALLER
    attention span than the static engine ever could
  - long prompts prefill in `prefill_chunk`-token pieces interleaved with
    decode chunks, so one long prompt never stalls the running batch
    (`models/llama.py prefill_chunk_paged` reads earlier chunks back from
    the pool — no growing inter-chunk state)
  - full prompt blocks are chain-hashed and shared across requests
    (refcounted; matches capped at plen-1 so sampling always has a logit)
  - pool exhaustion preempts the youngest running request by RECOMPUTE:
    its blocks are freed and it requeues with prompt+generated as the new
    prompt (emitted tokens are never re-emitted)

All device programs are static-shape (jit cache keyed on the (B, W, C)
buckets); block gathers/scatters are XLA gather/scatter on the block axis.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private.analysis.lock_witness import make_lock
from ray_tpu._private import device_telemetry
from ray_tpu.llm.config import GenerationConfig, LLMConfig
from ray_tpu.llm.engine import (
    _MAX_STOP_IDS,
    _MAX_TOP_K,
    _Request,
    _sample,
    _sample_dist,
)
from ray_tpu._private.prefix_hash import chain_hash, prefix_chain_hashes
from ray_tpu.models import llama
from ray_tpu.ops.rope import rope_frequencies


class BlockManager:
    """Host-side allocator + prefix cache over the device block pool.

    ``on_evict(block, chain_hash)`` fires when allocation pressure
    repurposes a hash-registered (cached) block, BEFORE its registration is
    dropped — the tier ladder's demotion hook: the engine copies the
    block's KV to the host-RAM tier while the pool still holds it."""

    def __init__(self, num_blocks: int, block_size: int,
                 prefix_caching: bool = True, on_evict=None):
        self.num_blocks = num_blocks
        self.bs = block_size
        self.prefix_caching = prefix_caching
        self.on_evict = on_evict
        # block 0 is the SINK: inactive decode slots' zero-padded table rows
        # make the device scatter land there, so it is never allocated —
        # a live request's data can never be corrupted by an idle slot.
        # TWO insertion-ordered free sets: plain (not hash-registered) and
        # cached (freed but revivable by match_prefix).  alloc drains plain
        # first, so prefix-cache entries are evicted only under real
        # pressure, oldest first — LRU-preserving allocation (the vLLM
        # free-list policy; without the split, pipelining's margin allocs
        # churned cached blocks while plain ones sat free).
        self.free_plain: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict((i, None) for i in range(1, num_blocks)))
        self.free_cached: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict())
        self.ref = [0] * num_blocks
        self.hash_of: Dict[int, int] = {}   # block -> chain hash
        self.by_hash: Dict[int, int] = {}   # chain hash -> block

    def num_free(self) -> int:
        return len(self.free_plain) + len(self.free_cached)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > self.num_free():
            return None
        out = []
        for _ in range(n):
            if self.free_plain:
                b, _ = self.free_plain.popitem(last=False)
            else:
                b, _ = self.free_cached.popitem(last=False)
            h = self.hash_of.pop(b, None)  # repurposed: stale cache entry out
            if h is not None and self.by_hash.get(h) == b:
                if self.on_evict is not None:
                    try:
                        self.on_evict(b, h)  # demote before the data is lost
                    except Exception:  # noqa: BLE001 — tiering is best-effort
                        pass
                del self.by_hash[h]
            self.ref[b] = 1
            out.append(b)
        return out

    def release(self, blocks: Sequence[int]):
        for b in blocks:
            self.ref[b] -= 1
            assert self.ref[b] >= 0, f"double free of block {b}"
            if self.ref[b] == 0:
                # still hash-registered blocks stay revivable by
                # match_prefix until allocation pressure evicts them
                if b in self.hash_of:
                    self.free_cached[b] = None
                else:
                    self.free_plain[b] = None

    def match_prefix(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest run of cached full blocks covering < len(prompt) tokens
        (the last token is always recomputed so sampling has a logit).
        Matched blocks are ref'd for the caller."""
        if not self.prefix_caching:
            return [], 0
        ids: List[int] = []
        h: Optional[int] = None
        limit = (len(prompt) - 1) // self.bs
        for i in range(limit):
            h = chain_hash(h, prompt[i * self.bs:(i + 1) * self.bs])
            b = self.by_hash.get(h)
            if b is None:
                break
            ids.append(b)
        for b in ids:
            if self.ref[b] == 0:
                self.free_cached.pop(b, None)  # revive a cached-free block
                self.free_plain.pop(b, None)
            self.ref[b] += 1
        return ids, len(ids) * self.bs

    def register(self, prompt: Sequence[int], blocks: Sequence[int]):
        """Register this sequence's full PROMPT blocks for future sharing."""
        if not self.prefix_caching:
            return
        h: Optional[int] = None
        for i in range(len(prompt) // self.bs):
            h = chain_hash(h, prompt[i * self.bs:(i + 1) * self.bs])
            b = blocks[i]
            if h not in self.by_hash and b not in self.hash_of:
                self.by_hash[h] = b
                self.hash_of[b] = h

    def adopt(self, block: int, h: int):
        """Register a chain hash for an already-allocated block (a tier
        revival: the caller just uploaded the cached KV into ``block``)."""
        if not self.prefix_caching:
            return
        if h not in self.by_hash and block not in self.hash_of:
            self.by_hash[h] = block
            self.hash_of[block] = h


# the reference/vLLM name for this role; the serve layer and ISSUE docs use
# it — one object, two names
BlockAllocator = BlockManager


class HostBlockCache:
    """Tiers 2+3 of the prefix-cache ladder: host-RAM LRU of full KV
    blocks keyed by chain hash, spilling to the plasma object store.

    HBM (tier 1) evictions demote here; ``get`` revives through host RAM
    first, then plasma (promoting the block back up).  Byte-capped LRU;
    plasma entries are ObjectRefs whose payloads live in the store (freed
    when the ref is dropped).  Thread-safe: the engine calls under its own
    lock, but the serve digest publisher reads concurrently."""

    def __init__(self, capacity_bytes: int, plasma_blocks: int = 0):
        self._cap = max(0, capacity_bytes)
        self._plasma_cap = max(0, plasma_blocks)
        self._entries: "collections.OrderedDict[int, Tuple]" = (
            collections.OrderedDict())  # hash -> (k_np, v_np)
        self._bytes = 0
        self._plasma: "collections.OrderedDict[int, object]" = (
            collections.OrderedDict())  # hash -> ObjectRef
        self._lock = make_lock("HostBlockCache._lock")

    def __len__(self):
        with self._lock:
            return len(self._entries) + len(self._plasma)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def hashes(self) -> List[int]:
        with self._lock:
            return list(self._plasma) + list(self._entries)

    def put(self, h: int, k, v):
        """Demote one block's KV into the host tier (LRU-evicting over the
        byte cap into plasma, or dropping when plasma is off/full)."""
        if self._cap <= 0:
            return
        from ray_tpu._private import runtime_metrics

        nbytes = k.nbytes + v.nbytes
        spill = []
        with self._lock:
            if h in self._entries:
                self._entries.move_to_end(h)
                return
            self._plasma.pop(h, None)  # promoted copy supersedes the spill
            self._entries[h] = (k, v)
            self._bytes += nbytes
            while self._bytes > self._cap and len(self._entries) > 1:
                eh, (ek, ev) = self._entries.popitem(last=False)
                self._bytes -= ek.nbytes + ev.nbytes
                spill.append((eh, ek, ev))
        for eh, ek, ev in spill:
            runtime_metrics.add_prefix_cache_evictions("host")
            self._spill_to_plasma(eh, ek, ev)

    def _spill_to_plasma(self, h: int, k, v):
        from ray_tpu._private import runtime_metrics

        if self._plasma_cap <= 0:
            return
        try:
            import ray_tpu

            if not ray_tpu.is_initialized():
                return
            ref = ray_tpu.put((k, v))
        except Exception:  # noqa: BLE001 — tiering is best-effort
            return
        with self._lock:
            self._plasma[h] = ref
            while len(self._plasma) > self._plasma_cap:
                self._plasma.popitem(last=False)
                runtime_metrics.add_prefix_cache_evictions("plasma")

    def get(self, h: int):
        """(k, v, tier) for a cached block, or None.  A plasma hit is
        promoted back into the host tier (it is about to be hot)."""
        with self._lock:
            got = self._entries.get(h)
            if got is not None:
                self._entries.move_to_end(h)
                return got[0], got[1], "host"
            ref = self._plasma.get(h)
        if ref is None:
            return None
        try:
            import ray_tpu

            k, v = ray_tpu.get(ref, timeout=5)
        except Exception:  # noqa: BLE001 — lost spill: treat as a miss
            with self._lock:
                self._plasma.pop(h, None)
            return None
        self.put(h, k, v)
        return k, v, "plasma"


@dataclasses.dataclass
class _PagedReq(_Request):
    blocks: List[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0      # prompt tokens already in the pool
    admitted_order: int = 0   # preemption picks the youngest
    # request-lifecycle stamps (serving SLO layer; only read when the
    # engine carries an slo_label — direct engine use books nothing)
    t_enqueue: float = 0.0
    t_admit: float = 0.0
    t_first_emit: float = 0.0
    # --- speculative decoding (engine._spec is not None) ---
    # draft-pool blocks mirroring this request's KV in the draft model's
    # pool; draft_prefill_pos tracks the draft's own chunked prefill
    # (a target prefix-cache hit does not help the draft — it recomputes
    # the matched region, cheap at draft size)
    draft_blocks: List[int] = dataclasses.field(default_factory=list)
    draft_prefill_pos: int = 0
    # False = this request decodes non-speculatively (draft-pool
    # exhaustion degrade, or a per-adapter opt-out) — zero drops
    spec_enabled: bool = False
    # acceptance bookkeeping (per-request speedup/acceptance metering)
    spec_proposed: int = 0
    spec_accepted: int = 0


def _bucket_pow2(n: int, lo: int = 1) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


def _spec_accept(pdist, qdist, drafted, key):
    """Rejection-sampling core of speculative verification (traced).

    pdist [B, k+1, V]: target distributions at each window position;
    qdist [B, k, V]: the draft distributions that generated ``drafted``
    [B, k] (zeroed rows disable speculation for that slot — acceptance
    is forced off and the correction residual degenerates to the target
    distribution itself).  Returns ``(a [B], corr [B])``: the count of
    leading accepted proposals and the correction token sampled from
    ``normalize(max(p_a - q_a, 0))`` — which, with ``q`` zero-padded at
    index k, IS the bonus-token draw from ``p_k`` on full acceptance.

    The standard speculative-sampling guarantee holds position-wise: the
    emitted token at each position is distributed exactly as the target
    distribution (pinned empirically in tests/test_specdec.py).  Greedy
    rows (one-hot dists from engine._sample_dist) collapse to exact
    longest-agreeing-prefix verification with argmax corrections."""
    b, k = drafted.shape
    key, ku, kr = jax.random.split(key, 3)
    u = jax.random.uniform(ku, (b, k))
    p_d = jnp.take_along_axis(pdist[:, :k], drafted[..., None], -1)[..., 0]
    q_d = jnp.take_along_axis(qdist, drafted[..., None], -1)[..., 0]
    # q_d > 0: a token the draft could not have drawn is never accepted
    # (a genuinely drafted token always has q_d > 0 — the categorical
    # cannot pick a zero-probability id — so this changes nothing on the
    # real path; it is what makes a ZEROED q row force a = 0, pinning
    # degraded slots' corrections to the position-0 target distribution)
    accept = (u * q_d < p_d) & (q_d > 0)
    a = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(1)  # [B] 0..k
    q_pad = jnp.concatenate(
        [qdist, jnp.zeros((b, 1, qdist.shape[-1]), qdist.dtype)], axis=1)
    p_a = jnp.take_along_axis(
        pdist, a[:, None, None].repeat(pdist.shape[-1], -1), 1)[:, 0]
    q_a = jnp.take_along_axis(
        q_pad, a[:, None, None].repeat(q_pad.shape[-1], -1), 1)[:, 0]
    resid = jnp.maximum(p_a - q_a, 0.0)
    resid = jnp.where(resid.sum(-1, keepdims=True) > 0, resid, p_a)
    # exact-zero residual entries get -inf weight (NOT log(x+eps): the
    # greedy one-hot path must have literally zero probability of
    # drawing a non-argmax token — the bit-parity pin)
    corr = jax.random.categorical(
        kr, jnp.where(resid > 0, jnp.log(resid), -jnp.inf),
        axis=-1).astype(jnp.int32)
    return a, corr


def _prefill_plan(plen: int, matched: int, chunk: int, bs: int):
    """Simulate the chunked-prefill loop: chunk widths are POW2-BUCKETED
    multiples of block_size (so the jit cache holds log2(chunk/bs) prefill
    programs per table bucket, not one per prefix-cache offset — an
    arbitrary-width chunk measured a 7.2 s XLA compile inside the serving
    window).  Returns the max block index any chunk's table must cover."""
    pos, cover = matched, matched // bs
    while pos < plen:
        rem = plen - pos
        c = min(chunk, _bucket_pow2(_pad_to(rem, bs), lo=bs))
        cover = max(cover, math.ceil((pos + c) / bs))
        pos += min(c, rem)
    return cover


def _prefill_cover_worst(plen: int, chunk: int, bs: int) -> int:
    """Max block index any prefill chunk of a ``plen``-token prompt can
    touch, over every possible prefix-cache offset.  Intermediate chunks
    never reach past plen; only the FINAL chunk's pow2 bucket overshoots,
    and a prefix hit merely shifts its start to another block boundary —
    so scanning block-aligned final-chunk starts bounds it exactly."""
    worst = 0
    lo = max(0, plen - chunk)
    start = ((lo + bs - 1) // bs) * bs
    for pos in range(start, plen, bs):
        c = min(chunk, _bucket_pow2(_pad_to(plen - pos, bs), lo=bs))
        worst = max(worst, math.ceil((pos + c) / bs))
    return worst


def _prefill_table_width(max_seq: int, chunk: int, bs: int) -> int:
    """True worst-case prefill table width: 1 (decode spare, reserved at
    admission) + the max block index any chunk dispatch can touch.

    ``max_blocks_per_seq + 2`` was NOT an upper bound: the final chunk's
    pow2 bucket can overshoot the prompt by up to ~chunk/2 tokens (e.g.
    max_seq=992, bs=16, chunk=256, plen=897 → the pos=768 chunk buckets
    to 256 wide and covers 1024 tokens = 65 blocks, past
    bucket_pow2(62+2)=64 — a broadcast ValueError mid-serve).  Only the
    last ~2*chunk prompt lengths can attain the max (any shorter plen
    covers ≤ plen + chunk, below the plen=max_seq floor), keeping the
    scan O(chunk²/bs) at engine init."""
    return 1 + max(
        _prefill_cover_worst(plen, chunk, bs)
        for plen in range(max(1, max_seq - 2 * chunk), max_seq + 1))


class PagedJaxLLMEngine:
    """Drop-in engine with the static engine's API over a paged KV pool.

    With ``config.speculative_config`` set, decode runs draft-model
    speculative: a small draft proposes k tokens per slot per step and
    the target verifies all k in ONE forward window (rejection sampling
    at temperature > 0; exact longest-agreeing-prefix at temperature 0 —
    greedy output is bit-identical to non-speculative decode).  The
    draft's KV lives in its own block pool under the same BlockManager
    machinery; draft-pool exhaustion degrades the affected request to
    plain decode (zero drops).
    """

    def __init__(self, config: LLMConfig, params=None, *, key=None,
                 draft_params=None):
        self.config = config
        cfg = config.model_config
        if cfg is None:
            raise ValueError("LLMConfig.model_config is required")
        self.cfg = cfg
        self.max_batch = config.max_batch_size
        self.max_seq = config.max_seq_len or cfg.max_seq_len
        self.bs = config.block_size
        if config.decode_chunk < 1:
            raise ValueError(
                f"decode_chunk must be >= 1 (got {config.decode_chunk})")
        if config.prefill_chunk % self.bs:
            raise ValueError(
                f"prefill_chunk ({config.prefill_chunk}) must be a multiple "
                f"of block_size ({self.bs})")
        nb = config.num_blocks
        if nb is None:
            # default pool: half the HBM the static cache would have used —
            # the demonstrable economics win; override via config.num_blocks
            nb = max(4, (self.max_batch * self.max_seq) // (2 * self.bs))
        self.num_blocks = nb
        self.max_blocks_per_seq = math.ceil(self.max_seq / self.bs)
        # FIXED prefill table width: per-request widths would key a jit
        # program per (chunk, width) combo, and prefix-cache hits reach
        # widths no warmup predicted — measured as multi-second XLA
        # compiles inside the serving window.  One width = at most
        # log2(prefill_chunk/bs) prefill programs, all warmed at init.
        # The masked overhang costs ~16% chunk compute at max_seq 1024.
        # Width = the simulated worst case over every prompt length and
        # chunk start (see _prefill_table_width) — pow2 chunk bucketing
        # can cover past max_blocks_per_seq + 2.
        self._prefill_w = _prefill_table_width(
            self.max_seq, config.prefill_chunk, self.bs)
        # tier ladder under the HBM chain-hash pool: HBM evictions demote
        # full prompt blocks to host RAM (and optionally plasma); a later
        # prefix match revives them by pool upload instead of recompute
        self._host_cache: Optional[HostBlockCache] = None
        if config.enable_prefix_caching and config.host_kv_cache_bytes > 0:
            self._host_cache = HostBlockCache(
                config.host_kv_cache_bytes, config.plasma_kv_cache_blocks)
        self.blocks = BlockManager(
            nb, self.bs, config.enable_prefix_caching,
            on_evict=(self._demote_block if self._host_cache is not None
                      else None))

        if params is None:
            params = llama.init_params(cfg, key or jax.random.PRNGKey(0))
        self.params = params
        cos, sin = rope_frequencies(cfg.head_dim, self.max_seq, cfg.rope_theta)
        self._rope = (jnp.asarray(cos), jnp.asarray(sin))

        from ray_tpu.llm.engine import (
            build_engine_mesh,
            pp_cache_spec,
            pp_param_specs,
        )

        pp = config.pipeline_parallel_size
        self.mesh = build_engine_mesh(cfg, config.tensor_parallel_size, pp,
                                      mesh=config.mesh)
        self.pool = llama.init_paged_kv_cache(cfg, nb, self.bs)
        if self.mesh is not None:
            from ray_tpu.parallel.mesh import shard_pytree

            self.params = shard_pytree(
                self.params,
                pp_param_specs(llama.inference_param_specs(cfg), pp),
                self.mesh)
            # the paged pool shards on the folded kv-head dim, matching
            # wk/wv's column sharding: each rank's cache scatter/gather
            # touches only its own head group — no resharding anywhere in
            # the decode dataflow.  The block table, BlockManager,
            # admission, prefix cache, and scheduling all stay host-side
            # and replicated: one logical engine over N devices.
            self.pool = shard_pytree(
                self.pool, pp_cache_spec(llama.paged_kv_cache_spec(), pp),
                self.mesh)
        # --- planner-routed TP collectives (tentpole, ISSUE 20) ---------
        # decode's per-layer allreduces are KiB-scale and latency-bound —
        # the α-β planner's flat/tree regime.  Plan once per program kind
        # at init (message sizes are static: B and chunk geometry are
        # compile-time), route the chosen algorithm into the jitted
        # programs as explicit shard_map collectives, and meter the
        # decision.  PP keeps GSPMD's implicit path (the layer scan spans
        # stages; an explicit island per stage boundary buys nothing).
        self._tp_plan = None          # llama.TPPlan for decode chunks
        self._tp_verify_plan = None   # ... for the spec-verify window
        self._tp_prefill_plan = None  # ... for prefill chunks
        self._tp_collectives = None   # {kind: plan_explain row} (bench)
        if (self.mesh is not None and config.tensor_parallel_size > 1
                and pp <= 1 and config.tp_planned_collectives):
            self._init_tp_planning()

        # host slot state (mirrors the static engine)
        self._slot_req: List[Optional[_PagedReq]] = [None] * self.max_batch
        self._lengths = np.zeros(self.max_batch, np.int32)
        self._next_tok = np.zeros(self.max_batch, np.int32)
        self._slot_temp = np.zeros(self.max_batch, np.float32)
        self._slot_topk = np.zeros(self.max_batch, np.int32)
        self._dirty = True
        self._d_next = self._d_lengths = self._d_active = None
        self._d_temp = self._d_topk = None
        self._d_remaining = self._d_stops = None
        self._d_key = jax.random.PRNGKey(cfg.vocab_size + 1)
        self._pending: "collections.deque[_PagedReq]" = collections.deque()
        self._requests: Dict[int, _PagedReq] = {}
        self._req_counter = 0
        self._admit_counter = 0
        self._lock = make_lock("PagedJaxLLMEngine._lock")
        # serving SLO layer: the hosting deployment's name, set via the
        # replica's set_slo_label threading (serve/_private/replica.py).
        # None (direct engine use) books no lifecycle stages at all.
        # Assigning a name also attaches device telemetry (slo_label is a
        # property) — the disabled path is self._telemetry staying None.
        self._slo_label: Optional[str] = None
        self._telemetry: Optional[device_telemetry.EngineTelemetry] = None
        # chunked-prefill budget spend, tracked per step for telemetry
        self._tel_prefill_budget = (config.prefill_token_budget
                                    or config.prefill_budget_tokens
                                    or config.prefill_chunk)
        self._tel_prefill_spent = 0
        # one decode chunk may stay IN FLIGHT while the host books the
        # previous chunk's tokens: the readback of chunk N overlaps chunk
        # N+1's device compute, hiding the dispatch+fence round trip
        # (~100 ms on a tunneled chip, ~3 ms/token-step at chunk 32).
        # (em_dev, active_slots, spec_slots): collected lazily by
        # _drain_locked(); spec_slots is () on the non-speculative path.
        self._inflight: Optional[Tuple] = None
        # monotonic ts of the last traced step's phase spans (rate limit)
        self._last_phase_span = float("-inf")
        # a finished prefill's sampled first token stays a DEVICE future
        # until the next drain point: a synchronous int(ids[0]) per request
        # serialized a ~100 ms readback behind every queued program
        # (measured: engine prefill 1,493 tok/s vs 13,000 tok/s for the
        # chunk program itself).  (slot, req, ids_future) tuples.
        self._first_pending: List[Tuple[int, _PagedReq, jnp.ndarray]] = []

        # fused pallas paged-attention kernel (ray_tpu/ops/paged_attention):
        # DMAs only each sequence's live pages — no gather materialization.
        # Default ON where it wins (measured v5e b32: ties the XLA gather at
        # span 256, 2.2x faster at span 1024 — benchmarks/paged_bisect.py).
        # Composes with TP via shard_map (kv heads over "tensor"); PP still
        # uses the gather path (the layer scan spans all stages, so a
        # pipeline-sharded pool cannot feed per-shard page DMAs).
        self._kernel_interpret = False
        supported = (llama.paged_kernel_supported(cfg)
                     and config.pipeline_parallel_size <= 1)
        want = config.paged_attention_kernel
        if want is None:
            self._use_kernel = supported
        elif want == "interpret":
            # explicit test hook: run the kernel in pallas interpret mode
            # off-TPU (exercises the TP shard_map plumbing on the virtual
            # CPU mesh).  Never chosen implicitly — interpret speed would
            # be a silent production footgun.
            if config.pipeline_parallel_size > 1:
                raise ValueError(
                    "paged_attention_kernel needs pipeline_parallel_size == 1")
            self._use_kernel = True
            self._kernel_interpret = jax.default_backend() != "tpu"
        elif want and not supported:
            raise ValueError(
                "paged_attention_kernel=True needs a TPU backend, "
                "head_dim % 128 == 0, and pipeline_parallel_size == 1")
        else:
            self._use_kernel = bool(want)
        self._decode = jax.jit(self._decode_chunk_impl, donate_argnums=2,
                               static_argnums=11)
        self._prefill_chunk = jax.jit(self._prefill_chunk_impl,
                                      donate_argnums=2)
        # tier revival: scatter one host-cached block back into the pool
        # (fixed shapes -> exactly one compile)
        self._upload_block = jax.jit(
            lambda pool, b, k, v: {"k": pool["k"].at[:, b].set(k),
                                   "v": pool["v"].at[:, b].set(v)},
            donate_argnums=0)
        # disaggregated handoff import: scatter a request's blocks (padded
        # to a pow2 count; pad rows land in sink block 0) into the pool
        self._import_blocks = jax.jit(
            lambda pool, idx, k, v: {"k": pool["k"].at[:, idx].set(k),
                                     "v": pool["v"].at[:, idx].set(v)},
            donate_argnums=0)

        # --- draft-model speculative decoding ---------------------------
        # The disabled path (speculative_config=None) stops HERE: no draft
        # pool, no extra programs, and step() pays one `is None` test.
        self._spec = config.speculative_config
        self._spec_k = 0
        if self._spec is not None:
            dcfg = self._spec.draft_model_config
            if dcfg is None:
                raise ValueError(
                    "speculative_config.draft_model_config is required")
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size {dcfg.vocab_size} != target "
                    f"{cfg.vocab_size} — verification compares token ids")
            k = int(self._spec.num_speculative_tokens)
            if k < 1:
                raise ValueError(
                    f"num_speculative_tokens must be >= 1 (got {k})")
            self._spec_k = k
            self._draft_cfg = dcfg
            if draft_params is None:
                draft_params = llama.init_params(
                    dcfg, key or jax.random.PRNGKey(1))
            self._draft_params = draft_params
            dcos, dsin = rope_frequencies(dcfg.head_dim, self.max_seq,
                                          dcfg.rope_theta)
            self._draft_rope = (jnp.asarray(dcos), jnp.asarray(dsin))
            dnb = self._spec.draft_num_blocks or nb
            self._draft_num_blocks = dnb
            # no prefix caching in the draft pool: draft KV is never
            # shared across requests (recompute at draft size is cheap,
            # and chain bookkeeping would double the admission work)
            self.draft_blocks = BlockManager(dnb, self.bs,
                                             prefix_caching=False)
            self._draft_pool = llama.init_paged_kv_cache(dcfg, dnb, self.bs)
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                from ray_tpu.parallel.mesh import shard_pytree

                # the draft stays single-chip: REPLICATE its params and
                # pool over the mesh (each device runs the tiny draft
                # redundantly).  Draft messages are so small that
                # allreduce α would dominate any sharding win — zero
                # collectives in every draft program, while the target's
                # verify window runs fully sharded.
                rep = jax.tree_util.tree_map(
                    lambda _: P(), llama.inference_param_specs(dcfg),
                    is_leaf=lambda x: isinstance(x, P))
                self._draft_params = shard_pytree(
                    self._draft_params, rep, self.mesh)
                self._draft_pool = shard_pytree(
                    self._draft_pool, {"k": P(), "v": P()}, self.mesh)
            self._d_spec = None  # device mirror of per-slot spec enable
            # draft chunked prefill: same chunk/table geometry as the
            # target (block_size is shared, so the fixed width carries)
            self._draft_prefill = jax.jit(
                lambda p, tok, pool, tab, p0: llama.prefill_chunk_paged(
                    self._draft_cfg, p, tok, pool, tab, p0,
                    rope_cache=self._draft_rope)[1],
                donate_argnums=2)
            self._draft_propose = jax.jit(self._draft_propose_impl,
                                          donate_argnums=2)
            self._spec_verify = jax.jit(self._spec_verify_impl,
                                        donate_argnums=4)
            # engine-lifetime acceptance totals (bench / specdec_stats)
            self._spec_proposed_total = 0
            self._spec_accepted_total = 0
            # finished requests' (proposed, accepted) for the serving
            # layer's per-request acceptance rows (bounded)
            self._spec_finished: "collections.OrderedDict[int, Tuple[int, int]]" = (
                collections.OrderedDict())

    # -- device telemetry ----------------------------------------------

    @property
    def slo_label(self) -> Optional[str]:
        return self._slo_label

    @slo_label.setter
    def slo_label(self, name: Optional[str]) -> None:
        self._slo_label = name
        if name is None:
            self._telemetry = None
            return
        # per-DEVICE bytes, not logical: a tp=N pool puts 1/N of its bytes
        # on each chip (the draft pool is replicated — full size per
        # device).  tree_nbytes of a sharded array counts GLOBAL bytes;
        # feeding that into hbm_split over-reported each device's
        # engine-owned HBM by N× on sharded replicas, making chip
        # telemetry and the disagg router's free-HBM digests lie.
        kv_bytes = device_telemetry.tree_nbytes_per_device(self.pool)
        if self._spec is not None:
            kv_bytes += device_telemetry.tree_nbytes_per_device(
                self._draft_pool)
        self._telemetry = device_telemetry.engine_telemetry_for(
            name,
            weights_bytes=device_telemetry.tree_nbytes_per_device(
                self.params),
            kv_pool_bytes=kv_bytes)
        if self._telemetry is not None:
            # local-mode / engine-direct utilization surface; serve
            # replicas additionally publish rows to the GCS KV
            device_telemetry.register_utilization_object(
                f"{name}:{id(self):x}", self)

    def utilization(self) -> dict:
        """Exact engine bookkeeping for ``state.utilization()``: slot and
        KV-block occupancy read from the live structures under the lock,
        plus the step-derived rates and HBM split when telemetry is
        attached.  Block 0 is the sink (never allocated), so capacity is
        ``num_blocks - 1``."""
        with self._lock:
            active = sum(1 for r in self._slot_req if r is not None)
            free = self.blocks.num_free()
            pending = len(self._pending)
        total = self.num_blocks - 1
        row = {
            "engine": "paged",
            "deployment": self._slo_label,
            "slots": {"active": active, "max": self.max_batch,
                      "free": self.max_batch - active},
            "kv_blocks": {"total": total, "free": free,
                          "used": total - free},
            "pending": pending,
        }
        tel = self._telemetry
        if tel is not None:
            rates = tel.rates()
            row["duty_cycle"] = rates["duty_cycle"]
            row["rates"] = rates
            row["hbm"] = tel.hbm_split()
        if self.mesh is not None:
            # mesh-aware view: KV/weights bytes PER DEVICE (the pool
            # shards its kv-head dim over "tensor"), plus the planned
            # collective decisions — what bench.py's busbw column and the
            # disagg digests read
            row["tp"] = {
                "degree": self.config.tensor_parallel_size,
                "pipeline": self.config.pipeline_parallel_size,
                "mesh_devices": int(np.asarray(self.mesh.devices).size),
                "mesh_shape": {k: int(v)
                               for k, v in dict(self.mesh.shape).items()
                               if int(v) > 1},
                "kv_bytes_per_device":
                    device_telemetry.tree_nbytes_per_device(self.pool),
                "weights_bytes_per_device":
                    device_telemetry.tree_nbytes_per_device(self.params),
                "planned_collectives": self._tp_collectives,
            }
        return row

    # -- planner-routed TP collectives ---------------------------------

    def _init_tp_planning(self):
        """Plan the per-layer decode/verify/prefill allreduces through the
        PR 10 α-β planner and stash per-kind :class:`llama.TPPlan` routing
        for the jitted programs.

        Message sizes are compile-time constants (every dispatch pads to
        ``max_batch`` and the chunk geometry is fixed), so one decision
        per kind covers steady state: zero plan lookups in the hot loop.
        Each decision is metered into ``ray_tpu_collective_plan_total``
        (algorithm + reason — flat/tree's "latency_bound" is decode's
        regime) and the full ``plan_explain`` row is kept for
        ``utilization()`` and bench.py's busbw column."""
        from ray_tpu.util.collective import planner as _planner
        from ray_tpu.util.collective.compression import CompressionSpec

        config, cfg = self.config, self.cfg
        axes = list(self.mesh.axis_names)
        dev_arr = np.asarray(self.mesh.devices)
        index = [0] * dev_arr.ndim
        index[axes.index("tensor")] = slice(None)
        tdevs = dev_arr[tuple(index)].ravel().tolist()
        topo = _planner.topology_for_devices(tdevs)
        # scheme "none" + hierarchical None = algorithm-only planning (no
        # quantization codec); min_bytes 0 because decode messages are
        # KiB-scale — the 64 KiB training default would force everything
        # stock before the cost model ever ran
        spec = CompressionSpec(scheme="none", min_bytes=0)
        allowed = ("flat", "ring", "tree")
        itemsize = jnp.dtype(cfg.compute_dtype).itemsize
        k = (int(config.speculative_config.num_speculative_tokens)
             if config.speculative_config is not None else 0)
        # the reduced payload is the [*, dim] partial-sum output of the
        # attn/FFN projections, in compute dtype
        kinds = {"decode": self.max_batch * cfg.dim * itemsize,
                 "prefill": config.prefill_chunk * cfg.dim * itemsize}
        if k:
            kinds["verify"] = self.max_batch * (k + 1) * cfg.dim * itemsize
        forced = config.tp_collective_algorithm
        rows = {}
        plans = {}
        for kind, nbytes in kinds.items():
            row = _planner.plan_explain(nbytes, topo, spec, allowed=allowed)
            if forced is not None:
                row = dict(row, chosen=forced, reason="forced")
            _planner.record_plan(row["chosen"], row["reason"])
            rows[kind] = row
            plans[kind] = llama.TPPlan(
                mesh=self.mesh, algorithm=row["chosen"],
                overlap=config.tp_overlap_collectives)
        self._tp_collectives = rows
        self._tp_plan = plans["decode"]
        self._tp_prefill_plan = plans["prefill"]
        self._tp_verify_plan = plans.get("verify")

    def _book_tp_collectives(self, kind: str, programs: int = 1,
                             nbytes_each: Optional[int] = None):
        """Meter one dispatch's planned TP collectives: 2 allreduces per
        layer per program (attn-out + FFN-down), bytes exact from the
        message size (``nbytes_each`` overrides the planned size for
        short prefill chunks), seconds from the α-β model (a modeled
        attribution — per-collective device timing isn't observable from
        the host without fencing the async dispatch pipeline).  The
        unsharded / planning-disabled path books NOTHING."""
        rows = self._tp_collectives
        row = rows.get(kind) if rows is not None else None
        if row is None:
            return
        from ray_tpu._private import runtime_metrics

        n = 2 * self.cfg.n_layers * programs
        cost = row["modeled_cost_s"].get(row["chosen"]) or 0.0
        runtime_metrics.observe_tp_collective(
            self.slo_label or "engine", row["chosen"], seconds=n * cost,
            nbytes=n * (nbytes_each if nbytes_each is not None
                        else row["nbytes"]))

    # -- jitted programs ------------------------------------------------

    def _decode_chunk_impl(self, params, tokens, pool, table, lengths, active,
                           remaining, stops, key, temps, top_ks, n_steps):
        """Multi-step paged decode (mirrors the static engine's program; the
        host guarantees every active slot's table covers lengths + n_steps
        tokens of appends)."""

        def one(carry, _):
            tokens, pool, lengths, active, remaining, key = carry
            logits, pool = llama.decode_step_paged(
                self.cfg, params, tokens, pool, table, lengths,
                rope_cache=self._rope, use_kernel=self._use_kernel,
                mesh=self.mesh, kernel_interpret=self._kernel_interpret,
                tp_plan=self._tp_plan)
            key, sub = jax.random.split(key)
            ids = _sample(logits, sub, temps, top_ks)
            emitted = jnp.where(active > 0, ids, -1)
            lengths = lengths + active
            remaining = remaining - active
            hit_stop = (stops == ids[:, None]).any(-1)
            done = (active > 0) & (hit_stop | (remaining <= 0)
                                   | (lengths + 1 >= self.max_seq))
            active = active * (1 - done.astype(active.dtype))
            tokens = jnp.where(active > 0, ids, tokens)
            return (tokens, pool, lengths, active, remaining, key), emitted

        carry = (tokens, pool, lengths, active, remaining, key)
        carry, emitted = jax.lax.scan(one, carry, None, length=n_steps)
        tokens, pool, lengths, active, remaining, key = carry
        return emitted, tokens, pool, lengths, active, remaining, key

    def _prefill_chunk_impl(self, params, tokens, pool, table, p0,
                            sample_idx, key, temp, top_k):
        """One chunk; also samples the token at chunk-local position
        ``sample_idx`` (the caller uses it only on the final chunk)."""
        logits, pool = llama.prefill_chunk_paged(
            self.cfg, params, tokens, pool, table, p0, rope_cache=self._rope,
            tp_plan=self._tp_prefill_plan)
        key, sub = jax.random.split(key)
        ids = _sample(logits[:, sample_idx], sub, temp, top_k)
        return ids, pool, key

    def _draft_propose_impl(self, params, tokens, pool, table, lengths,
                            key, temps, top_ks):
        """k+1 autoregressive draft steps per slot: step j feeds the
        running token at position lengths+j and samples the next proposal.
        Steps 0..k-1 yield the k proposals; step k exists only to WRITE
        the last proposal's draft KV (on full acceptance the next cycle
        starts at lengths+k+1, and the draft's attention span must cover
        position lengths+k — without the extra step the draft pool would
        silently fall one token behind after every full accept).

        Returns (drafted [k, B], qdist [k, B, V] — the exact per-step
        sampling distributions, for rejection sampling — updated pool,
        key).  Positions clamp at max_seq-1: a slot that close to the
        end finishes this cycle, and the clamped writes only ever clobber
        draft KV of a sequence about to free its slot."""
        k = self._spec_k

        def one(carry, j):
            tok, pool, key = carry
            cur = jnp.minimum(lengths + j, self.max_seq - 1)
            logits, pool = llama.decode_step_paged(
                self._draft_cfg, params, tok, pool, table, cur,
                rope_cache=self._draft_rope)
            key, sub = jax.random.split(key)
            ids = _sample(logits, sub, temps, top_ks)
            q = _sample_dist(logits, temps, top_ks)
            return (ids, pool, key), (ids, q)

        (_, pool, key), (drafted, qdist) = jax.lax.scan(
            one, (tokens, pool, key), jnp.arange(k + 1))
        return drafted[:k], qdist[:k], pool, key

    def _spec_verify_impl(self, params, tokens, drafted, qdist, pool, table,
                          lengths, active, remaining, stops, key, temps,
                          top_ks, spec):
        """Verify k drafted tokens per slot in ONE target forward.

        The window [t0, d_1..d_k] runs through ``decode_window_paged``
        (KV written at positions lengths..lengths+k; rejected positions'
        KV goes stale and is overwritten by later steps — attention masks
        by length, so stale KV is never read).  Acceptance is standard
        rejection sampling — accept d_j iff u*q(d_j) < p(d_j), correction
        from normalize(max(p-q, 0)), bonus from p_k on full acceptance —
        where greedy rows' distributions are exact argmax one-hots
        (engine._sample_dist), which COLLAPSES the same arithmetic to
        exact longest-agreeing-prefix verification: greedy output is
        bit-identical to non-speculative decode.  Slots with spec=0
        (degraded / draft disabled) force zero acceptances and a zeroed
        draft distribution, making their single emission an exact plain
        decode step.  Stop-token / budget / max_seq handling mirrors the
        non-speculative scan ORDER-EXACTLY over the emission sequence.

        Returns (emitted [k+1, B] (-1 padded), accepted [B] — the TRUE
        per-slot acceptance count, BEFORE stop/budget/max_seq truncation
        of the emission window, so metered acceptance measures draft
        quality rather than conflating it with a request's final-cycle
        truncation — next tokens, pool, lengths, active, remaining,
        key); the emitted matrix matches the chunked decode program's
        contract, so collection reuses the pipeline."""
        k = self._spec_k
        b = tokens.shape[0]
        window = jnp.concatenate([tokens[:, None], drafted.T], axis=1)
        logits, pool = llama.decode_window_paged(
            self.cfg, params, window, pool, table, lengths,
            rope_cache=self._rope, pos_limit=self.max_seq,
            tp_plan=self._tp_verify_plan)
        # per-position target distributions under each slot's sampling
        # params — exactly what non-speculative _sample would draw from
        pdist = jax.vmap(lambda lg: _sample_dist(lg, temps, top_ks),
                         in_axes=1, out_axes=1)(logits)  # [B, k+1, V]
        d = drafted.T  # [B, k]
        # zero the draft distribution for non-spec slots: acceptance is
        # forced off (u*0 < p never accepts a q-impossible token... and
        # the explicit mask below makes it unconditional) AND the
        # correction residual max(p - 0, 0) becomes p itself — their one
        # emission is an exact plain decode sample
        q = qdist.transpose(1, 0, 2) * (spec[:, None, None] > 0)
        key, ka = jax.random.split(key)
        a, corr = _spec_accept(pdist, q, d, ka)
        idx = jnp.arange(k + 1)[None, :]
        # candidate emission j: accepted draft for j < a, correction at a
        e = jnp.where(idx < a[:, None],
                      jnp.pad(d, ((0, 0), (0, 1))), corr[:, None])
        # sequential stop/budget/max_seq semantics, mirroring the
        # non-speculative scan: emission j implies lengths+j+1 written
        # tokens and remaining-(j+1) budget; the first done truncates
        base = (idx <= a[:, None]) & (active[:, None] > 0)
        hit_stop = (stops[:, None, :] == e[..., None]).any(-1)
        done_at = (hit_stop
                   | (remaining[:, None] - (idx + 1) <= 0)
                   | (lengths[:, None] + idx + 2 >= self.max_seq))
        stopped_before = jnp.cumsum(
            jnp.pad((base & done_at).astype(jnp.int32),
                    ((0, 0), (1, 0)))[:, :-1], axis=1) > 0
        valid = base & ~stopped_before
        emitted = jnp.where(valid, e, -1).astype(jnp.int32).T  # [k+1, B]
        n_emit = valid.sum(1)
        new_len = lengths + n_emit
        new_rem = remaining - n_emit
        done = (valid & done_at).any(1)
        new_active = active * (1 - done.astype(active.dtype))
        last = jnp.take_along_axis(
            e, jnp.maximum(n_emit - 1, 0)[:, None], 1)[:, 0]
        new_tok = jnp.where(new_active > 0, last, tokens).astype(jnp.int32)
        return (emitted, a.astype(jnp.int32), new_tok, pool, new_len,
                new_active, new_rem, key)

    # -- request lifecycle ---------------------------------------------

    def add_request(self, prompt: Sequence[int],
                    gen: Optional[GenerationConfig] = None) -> int:
        gen = gen or GenerationConfig()
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(gen.stop_token_ids) > _MAX_STOP_IDS:
            raise ValueError(
                f"at most {_MAX_STOP_IDS} stop_token_ids supported "
                f"(got {len(gen.stop_token_ids)})")
        if gen.top_k > _MAX_TOP_K:
            raise ValueError(
                f"top_k is capped at {_MAX_TOP_K} (got {gen.top_k}) — the "
                "kth threshold comes from a fixed-width lax.top_k")
        if len(prompt) + gen.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({gen.max_new_tokens})"
                f" exceeds max_seq_len {self.max_seq}")
        worst = math.ceil((len(prompt) + gen.max_new_tokens + 1) / self.bs)
        # admission reserves cover+1 blocks (chunk-bucket overhang included,
        # any prefix offset) — an infeasible reserve must fail HERE, not
        # retry forever in _admit_locked
        worst = max(worst, 1 + _prefill_cover_worst(
            len(prompt), self.config.prefill_chunk, self.bs))
        if worst > self.num_blocks - 1:  # block 0 is the sink
            raise ValueError(
                f"request needs up to {worst} KV blocks but the pool has "
                f"{self.num_blocks} — raise num_blocks or lower max_new_tokens")
        with self._lock:
            self._req_counter += 1
            req = _PagedReq(self._req_counter, list(prompt), gen)
            req.spec_enabled = self._spec is not None
            if self.slo_label is not None:
                req.t_enqueue = time.monotonic()
            self._requests[req.request_id] = req
            self._pending.append(req)
            return req.request_id

    def has_work(self) -> bool:
        with self._lock:
            return (bool(self._pending) or self._inflight is not None
                    or any(r is not None for r in self._slot_req))

    # -- tiered prefix cache --------------------------------------------

    def _demote_block(self, block: int, h: int):
        """BlockManager eviction hook: copy the repurposed cached block's
        KV to the host tier before the pool overwrites it.  One small
        device->host readback per eviction — off the steady decode path
        (it only fires under real allocation pressure); free blocks are
        never written by in-flight programs, so the read is consistent."""
        from ray_tpu._private import runtime_metrics

        k = np.asarray(self.pool["k"][:, block])
        v = np.asarray(self.pool["v"][:, block])
        self._host_cache.put(h, k, v)
        runtime_metrics.add_prefix_cache_evictions("hbm")

    def _match_prefix_tiered(self, prompt: Sequence[int]):
        """HBM chain match, then extend the chain through the host/plasma
        tiers: each tier hit allocates a pool block, uploads the cached KV
        and re-registers the link, so the revived prefix is an ordinary
        HBM match for every later request.

        Returns ``(shared, matched, (hbm_hits, misses, revived_tiers))``:
        NO metrics are booked here — the caller books them only on a
        SUCCESSFUL admission.  A pool-full head-of-line request re-matches
        every step, so booking per attempt would fabricate phantom counts;
        and a block revived on a failed attempt re-matches as an ordinary
        HBM hit on the retry (adopt registered it), so hits + misses must
        always sum to the prompt's block count per admission."""
        shared, matched = self.blocks.match_prefix(prompt)
        if not self.blocks.prefix_caching:
            return shared, matched, (0, 0, ())
        limit = (len(prompt) - 1) // self.bs
        hbm_hits = len(shared)
        revived = []
        if self._host_cache is not None and len(shared) < limit:
            chain = prefix_chain_hashes(prompt, self.bs, limit=limit)
            i = len(shared)
            while i < limit:
                got = self._host_cache.get(chain[i])
                if got is None:
                    break
                fresh = self.blocks.alloc(1)
                if fresh is None:
                    break  # pool full: revival loses to live requests
                k, v, tier = got
                b = fresh[0]
                kd = self.pool["k"].dtype
                self.pool = self._upload_block(
                    self.pool, jnp.int32(b),
                    jnp.asarray(np.asarray(k, dtype=kd)),
                    jnp.asarray(np.asarray(v, dtype=kd)))
                self.blocks.adopt(b, chain[i])
                shared.append(b)
                revived.append(tier)
                i += 1
        return (shared, len(shared) * self.bs,
                (hbm_hits, limit - len(shared), tuple(revived)))

    def prefix_digest(self, max_hashes: Optional[int] = None) -> Dict:
        """Compact summary of the prefix chains this engine can serve
        without recompute (HBM registrations + host/plasma tiers), newest
        last.  The serve router compares request chains against it
        (cache-aware routing); hashes are stable across processes
        (_private/prefix_hash.py)."""
        if not self.config.enable_prefix_caching:
            return {"block_size": self.bs, "hashes": []}
        if max_hashes is None:
            from ray_tpu._private.config import global_config

            max_hashes = global_config().serve_prefix_digest_max_hashes
        with self._lock:
            hashes = list(self.blocks.by_hash)
        if self._host_cache is not None:
            seen = set(hashes)
            hashes = [h for h in self._host_cache.hashes()
                      if h not in seen] + hashes
        if len(hashes) > max_hashes:
            hashes = hashes[-max_hashes:]
        return {"block_size": self.bs, "hashes": hashes}

    # -- speculative decoding surfaces ----------------------------------

    def specdec_stats(self) -> Optional[Dict[str, float]]:
        """Engine-lifetime acceptance totals, or None with speculation
        off (the same books-nothing shape as the metric families)."""
        if self._spec is None:
            return None
        with self._lock:
            p, a = self._spec_proposed_total, self._spec_accepted_total
        return {"k": self._spec_k, "proposed": p, "accepted": a,
                "acceptance_rate": (a / p) if p else 0.0}

    def specdec_request_stats(self, request_id: int):
        """(proposed, accepted) for a FINISHED request, or None (unknown
        id, speculation off, or the request never speculated) — the
        serving layer attaches this to the request's SLO recent-row."""
        if self._spec is None:
            return None
        with self._lock:
            return self._spec_finished.get(request_id)

    # -- admission / prefill -------------------------------------------

    def _admit_locked(self):
        """Memory-based admission: a pending request enters when the pool
        has blocks for its full (chunk-padded) prompt plus one decode block
        — proportional to ACTUAL prompt length, never max_seq.  Reserving
        the prompt up front (instead of chunk-by-chunk) makes the system
        livelock-free: a mid-prefill request can never stall on allocation,
        so every admitted request reaches the preemptible decode state."""
        for slot in range(self.max_batch):
            if not self._pending or self._slot_req[slot] is not None:
                continue
            req = self._pending[0]
            shared, matched, hit_miss = self._match_prefix_tiered(req.prompt)
            # reserve every block any (pow2-bucketed) prefill chunk's table
            # must cover — chunk padding may reach past the prompt's own
            # blocks (trimmed at prefill end); +1 is the first decode
            # write's spare
            cover = _prefill_plan(len(req.prompt), matched,
                                  self.config.prefill_chunk, self.bs)
            need = cover - len(shared) + 1
            fresh = self.blocks.alloc(need)
            if fresh is None:
                self.blocks.release(shared)
                return  # pool full: keep FIFO order, retry next step
            if req.spec_enabled:
                # the draft prefills the WHOLE prompt (no prefix cache in
                # the draft pool), so it needs the full chunk-padded cover
                dcover = _prefill_plan(len(req.prompt), 0,
                                       self.config.prefill_chunk, self.bs)
                dfresh = self.draft_blocks.alloc(dcover + 1)
                if dfresh is None:
                    # draft-pool exhaustion degrades THIS request to
                    # plain decode — never blocks admission (zero drops)
                    req.spec_enabled = False
                else:
                    req.draft_blocks = dfresh
                    req.draft_prefill_pos = 0
            if self.blocks.prefix_caching:
                from ray_tpu._private import runtime_metrics

                hbm_hits, misses, revived = hit_miss
                runtime_metrics.add_prefix_cache_hits("hbm", hbm_hits)
                for tier in revived:
                    runtime_metrics.add_prefix_cache_hits(tier)
                runtime_metrics.add_prefix_cache_misses(misses)
            self._pending.popleft()
            req.slot = slot
            req.blocks = shared + fresh
            req.prefill_pos = matched
            self._admit_counter += 1
            req.admitted_order = self._admit_counter
            self._slot_req[slot] = req
            if self.slo_label is not None and req.t_enqueue:
                # first admission only: a preempted request re-queues with
                # t_admit already set — its queue_wait was booked once
                if not req.t_admit:
                    from ray_tpu.serve._private import slo

                    req.t_admit = time.monotonic()
                    slo.record_stage(self.slo_label, "queue_wait",
                                     req.t_admit - req.t_enqueue)
                else:
                    req.t_admit = time.monotonic()

    def _decode_ready(self, req: _PagedReq) -> bool:
        """A slot joins the decode batch only when its target prefill —
        and, when speculating, its draft prefill — covers the prompt."""
        plen = len(req.prompt)
        if req.prefill_pos < plen:
            return False
        return not req.spec_enabled or req.draft_prefill_pos >= plen

    def _draft_prefill_chunk_locked(self, req: _PagedReq,
                                    seq: Optional[Sequence[int]] = None):
        """Dispatch one draft prefill chunk (same pow2 chunk geometry and
        fixed table width as the target — block_size is shared).  ``seq``
        overrides the sequence being prefilled (default: the prompt): a
        mid-decode migration import re-seeds the draft over
        prompt + generated history so the draft can propose from the
        resume position."""
        seq = req.prompt if seq is None else seq
        plen = len(seq)
        remaining = plen - req.draft_prefill_pos
        c = min(self.config.prefill_chunk,
                _bucket_pow2(_pad_to(remaining, self.bs), lo=self.bs))
        p0 = req.draft_prefill_pos
        need = math.ceil((p0 + c) / self.bs)
        assert need <= len(req.draft_blocks), (
            f"draft prefill chunk not covered: need {need} blocks, "
            f"have {len(req.draft_blocks)} (draft admission reserve bug)")
        take = min(c, remaining)
        tokens = np.zeros((1, c), np.int32)
        tokens[0, :take] = seq[p0:p0 + take]
        table = np.zeros((1, self._prefill_w), np.int32)
        table[0, :len(req.draft_blocks)] = req.draft_blocks
        self._draft_pool = self._draft_prefill(
            self._draft_params, jnp.asarray(tokens), self._draft_pool,
            jnp.asarray(table), jnp.int32(p0))
        req.draft_prefill_pos = p0 + take
        if req.draft_prefill_pos >= plen:
            # trim chunk-padding draft blocks down to the prompt cover
            keep = math.ceil(plen / self.bs)
            if len(req.draft_blocks) > keep:
                self.draft_blocks.release(req.draft_blocks[keep:])
                del req.draft_blocks[keep:]
            self._dirty = True

    def _prefill_step_locked(self):
        """Advance mid-prefill slots, one chunk per slot, until the step's
        token budget (config.prefill_token_budget, default one chunk) is
        spent — chunked-prefill scheduling: prefill interleaves with
        decode at a bounded per-step cost (the vLLM
        max_num_batched_tokens analog) so a long prompt can never starve
        decode ITL, while a burst of arrivals still ramps many slots per
        step.  Prefill dispatches are pipelined: only a FINAL chunk's
        sampled token syncs the host.  Blocks were reserved at admission
        — no allocation can fail here.

        With speculation, the draft model prefills the same prompt into
        its own pool: after each target chunk the draft catches up to the
        target position (draft chunks ride outside the token budget —
        the budget bounds TARGET compute, and draft chunks are a small
        fraction of it; a target prefix-cache hit makes the draft replay
        the matched region, still cheap at draft size)."""
        budget = (self.config.prefill_token_budget
                  or self.config.prefill_budget_tokens
                  or self.config.prefill_chunk)
        self._tel_prefill_budget = budget
        progress = True
        while budget > 0 and progress:
            # round-robin over mid-prefill slots, one chunk each, until
            # the budget is spent: a burst of arrivals ramps many slots
            # per step AND a lone long prompt can use the whole budget
            # (multiple chunks per step) instead of silently pacing at
            # one chunk regardless of the knob
            progress = False
            for slot in range(self.max_batch):
                if budget <= 0:
                    return
                req = self._slot_req[slot]
                if req is None or self._decode_ready(req):
                    continue
                plen = len(req.prompt)
                if req.prefill_pos >= plen:
                    # target done, draft lagging: catch up (defensive —
                    # the frontier loop below keeps them in lockstep)
                    while req.draft_prefill_pos < plen:
                        self._draft_prefill_chunk_locked(req)
                    continue
                remaining = plen - req.prefill_pos
                c = min(self.config.prefill_chunk,
                        _bucket_pow2(_pad_to(remaining, self.bs),
                                     lo=self.bs))
                need = math.ceil((req.prefill_pos + c) / self.bs)
                assert need <= len(req.blocks), (
                    f"prefill chunk not covered: need {need} blocks, "
                    f"have {len(req.blocks)} (admission reserve bug)")
                p0 = req.prefill_pos
                take = min(c, remaining)
                tokens = np.zeros((1, c), np.int32)
                tokens[0, :take] = req.prompt[p0:p0 + take]
                table = np.zeros((1, self._prefill_w), np.int32)
                table[0, :len(req.blocks)] = req.blocks
                is_last = p0 + take >= plen
                sample_idx = (plen - 1 - p0) if is_last else 0
                ids, self.pool, self._d_key = self._prefill_chunk(
                    self.params, jnp.asarray(tokens), self.pool,
                    jnp.asarray(table), jnp.int32(p0),
                    jnp.int32(sample_idx), self._d_key,
                    jnp.asarray([req.gen.temperature], np.float32),
                    jnp.asarray([req.gen.top_k], np.int32))
                if self._tp_collectives is not None:
                    self._book_tp_collectives(
                        "prefill",
                        nbytes_each=c * self.cfg.dim
                        * jnp.dtype(self.cfg.compute_dtype).itemsize)
                req.prefill_pos = p0 + take
                # the draft tracks the target's prefill frontier
                while (req.spec_enabled
                       and req.draft_prefill_pos < min(req.prefill_pos,
                                                       plen)):
                    self._draft_prefill_chunk_locked(req)
                progress = True
                if is_last:
                    if self.slo_label is not None and req.t_admit:
                        from ray_tpu.serve._private import slo

                        slo.record_stage(self.slo_label, "prefill",
                                         time.monotonic() - req.t_admit)
                    # trim chunk-padding blocks; decode's ensure pass
                    # re-allocates
                    keep = math.ceil(plen / self.bs)
                    if len(req.blocks) > keep:
                        self.blocks.release(req.blocks[keep:])
                        del req.blocks[keep:]
                    self.blocks.register(req.prompt, req.blocks)
                    self._lengths[slot] = plen
                    self._slot_temp[slot] = req.gen.temperature
                    self._slot_topk[slot] = req.gen.top_k
                    self._first_pending.append((slot, req, ids))
                    self._dirty = True
                budget -= take
                self._tel_prefill_spent += take

    def _emit_locked(self, req: _PagedReq, token: int):
        req.out_tokens.append(token)
        if self.slo_label is not None and not req.t_first_emit:
            req.t_first_emit = time.monotonic()
        if (token in req.gen.stop_token_ids
                or len(req.out_tokens) >= req.gen.max_new_tokens
                or self._lengths[req.slot] + 1 >= self.max_seq):
            req.done = True
            if self.slo_label is not None and req.t_first_emit:
                from ray_tpu.serve._private import slo

                slo.record_stage(self.slo_label, "decode",
                                 time.monotonic() - req.t_first_emit)
            if self._spec is not None and req.spec_proposed:
                # retain per-request acceptance for the serving layer's
                # recent-request rows (bounded ring; read via
                # specdec_request_stats after the request is gone)
                self._spec_finished[req.request_id] = (
                    req.spec_proposed, req.spec_accepted)
                while len(self._spec_finished) > 1024:
                    self._spec_finished.popitem(last=False)
            self._free_slot_locked(req)

    def _free_slot_locked(self, req: _PagedReq):
        self.blocks.release(req.blocks)
        req.blocks = []
        if req.draft_blocks:
            self.draft_blocks.release(req.draft_blocks)
            req.draft_blocks = []
        self._slot_req[req.slot] = None
        self._lengths[req.slot] = 0
        req.slot = -1
        self._dirty = True

    def _preempt_locked(self, exclude_slot: int = -1) -> bool:
        """Evict the youngest decode-active request by recompute: free its
        blocks, requeue with prompt+generated as the new prompt.  The OLDEST
        active request is never evicted — it always wins block contention,
        so it completes and the system makes progress (no preemption
        livelock)."""
        candidates = [r for r in self._slot_req
                      if r is not None and r.slot != exclude_slot
                      and r.prefill_pos >= len(r.prompt)]
        if len(candidates) < 2:
            return False  # never evict the sole (oldest) runner
        oldest = min(c.admitted_order for c in candidates)
        victim = max((c for c in candidates if c.admitted_order > oldest),
                     key=lambda c: c.admitted_order, default=None)
        if victim is None:
            return False
        victim.prompt = victim.prompt + victim.out_tokens
        victim.prefill_pos = 0
        victim.draft_prefill_pos = 0
        self._free_slot_locked(victim)
        # recompute re-prefills the draft pool too, so a request degraded
        # by earlier draft-pool pressure gets a fresh chance to speculate
        victim.spec_enabled = self._spec is not None
        victim.done = False
        self._pending.appendleft(victim)
        self._dirty = True
        return True

    # -- decode ---------------------------------------------------------

    def _ensure_decode_blocks_locked(self, margin: int) -> List[int]:
        """Every decode-active slot's table must cover lengths + margin
        appends before dispatch (allocation is host-side; the device program
        is static). Returns the decode-active slot list."""
        restart = True
        while restart:
            restart = False
            active = []
            for s in range(self.max_batch):
                req = self._slot_req[s]
                if req is None or not self._decode_ready(req):
                    continue
                while True:
                    need = math.ceil(
                        (int(self._lengths[s]) + margin) / self.bs)
                    need = min(need, self.max_blocks_per_seq)
                    deficit = need - len(req.blocks)
                    if deficit <= 0:
                        self._ensure_draft_blocks_locked(req, need)
                        active.append(s)
                        break
                    fresh = self.blocks.alloc(deficit)
                    if fresh is not None:
                        req.blocks.extend(fresh)
                        active.append(s)
                        break
                    if self._inflight is not None:
                        # the in-flight chunk may still WRITE blocks a
                        # victim owns — never free them under it.  The
                        # drain advances lengths AND trims margin blocks
                        # off slots already validated this pass, so every
                        # coverage decision so far is stale: restart the
                        # whole pass (the drain can happen at most once).
                        self._drain_locked()
                        restart = True
                        break
                    if not self._preempt_locked():
                        # can't evict anyone else; run without this slot
                        # rather than deadlock (it keeps its blocks and
                        # retries)
                        break
                    if self._slot_req[s] is None:
                        break  # we were the youngest and got evicted
                if restart:
                    break
        return [s for s in active if self._slot_req[s] is not None]

    def _ensure_draft_blocks_locked(self, req: _PagedReq, need: int):
        """Draft-pool coverage for a decode-ready speculating slot.
        Exhaustion NEVER preempts or stalls anyone: the request simply
        degrades to plain decode (spec_enabled=False, its draft blocks
        returned to the pool) — the documented zero-drop behavior.  A
        degraded request stays degraded for this residency (its draft KV
        is gone; recompute after preemption re-enables speculation)."""
        if not req.spec_enabled:
            return
        deficit = need - len(req.draft_blocks)
        if deficit <= 0:
            return
        fresh = self.draft_blocks.alloc(deficit)
        if fresh is not None:
            req.draft_blocks.extend(fresh)
            return
        self.draft_blocks.release(req.draft_blocks)
        req.draft_blocks = []
        req.spec_enabled = False
        self._dirty = True  # the device spec mask must refresh

    def _trim_locked(self, margin: int = 0):
        """Return over-allocated chunk blocks (sequence stopped early).
        ``margin``: appends the device may still make (an in-flight chunk)
        beyond the host's view of lengths — those blocks must be kept."""
        for s in range(self.max_batch):
            req = self._slot_req[s]
            if req is None or req.prefill_pos < len(req.prompt):
                continue
            keep = max(1, math.ceil(
                (int(self._lengths[s]) + margin + 1) / self.bs))
            if len(req.blocks) > keep:
                self.blocks.release(req.blocks[keep:])
                del req.blocks[keep:]
            if req.draft_blocks and len(req.draft_blocks) > keep:
                self.draft_blocks.release(req.draft_blocks[keep:])
                del req.draft_blocks[keep:]

    def _collect_locked(self, em_dev, active: List[int], margin: int,
                        spec_slots: Sequence[int] = (), acc_dev=None):
        """Book one finished decode chunk's tokens into host state
        (lengths, next token, done transitions, block trims).  ``margin``:
        appends another still-in-flight chunk may make beyond this one.
        ``spec_slots``: slots that ran this chunk WITH speculation —
        their acceptance is metered from ``acc_dev`` (the verifier's TRUE
        per-slot accepted counts; deriving accepted from the emission
        matrix would conflate draft rejection with stop/budget/max_seq
        truncation of a request's final cycle and bias acceptance low
        exactly for short generations) BEFORE the emit loop, so a
        request finishing mid-collect reports final stats at its
        terminal booking.  Dead slots (zero emissions) book nothing."""
        em = np.asarray(em_dev)  # fences this chunk (a later one may run on)
        if spec_slots:
            acc = np.asarray(acc_dev)
            proposed = accepted = 0
            k = self._spec_k
            for s in spec_slots:
                req = self._slot_req[s]
                if int((em[:, s] >= 0).sum()) <= 0:
                    continue
                got = min(int(acc[s]), k)
                proposed += k
                accepted += got
                if req is not None:
                    req.spec_proposed += k
                    req.spec_accepted += got
            if proposed:
                self._spec_proposed_total += proposed
                self._spec_accepted_total += accepted
                self._book_specdec(proposed, accepted)
        for t in range(em.shape[0]):
            for s in active:
                req = self._slot_req[s]
                if req is None:
                    continue
                tok = int(em[t, s])
                if tok < 0:
                    continue
                self._lengths[s] += 1
                self._next_tok[s] = tok
                self._emit_locked(req, tok)
        self._trim_locked(margin=margin)

    def _book_specdec(self, proposed: int, accepted: int):
        """Meter drafted/accepted token counts into the runtime-metrics
        families and the serving SLO ledger.  Only ever called with
        speculation configured — the disabled path books NOTHING (the
        same invariant as the PR 9 lifecycle layer)."""
        from ray_tpu._private import runtime_metrics

        dep = self.slo_label or "engine"
        runtime_metrics.add_specdec_tokens(dep, proposed, accepted)
        if self.slo_label is not None:
            from ray_tpu.serve._private import slo

            # ledger-side fold (state.serving_slo()); records under the
            # process ledger's lock only — never an RPC under step()'s
            # engine lock
            slo.note_specdec(self.slo_label, proposed, accepted)

    def _resolve_first_tokens_locked(self):
        """Book pending first-token futures (one sync covers them all —
        their programs finished long before the drain that calls this)."""
        pending, self._first_pending = self._first_pending, []
        for slot, req, ids in pending:
            if self._slot_req[slot] is not req:
                continue  # preempted before its first token surfaced:
                # recompute will re-sample it (it was never emitted)
            first = int(np.asarray(ids)[0])
            self._next_tok[slot] = first
            self._emit_locked(req, first)

    def _drain_locked(self):
        """Collect the in-flight decode chunk, if any, and any pending
        first tokens."""
        if self._inflight is not None:
            em_dev, active, spec_slots, acc_dev = self._inflight
            self._inflight = None
            self._collect_locked(em_dev, active, margin=0,
                                 spec_slots=spec_slots, acc_dev=acc_dev)
        self._resolve_first_tokens_locked()

    def step(self, decode: bool = True) -> Dict[int, List[int]]:
        """One engine step: admit, one prefill chunk, one decode chunk.

        Steady-state full-batch decode PIPELINES: the chunk dispatched here
        is collected on the NEXT step, so its device compute overlaps this
        step's host bookkeeping and readback latency.  Any non-steady event
        (admission, prefill, a finished request, preemption pressure)
        drains the in-flight chunk first — correctness never depends on
        the lagged view.  ``decode=False`` runs admission/prefill only
        (ramp control)."""
        emitted: Dict[int, List[int]] = {}
        # engine phases become children of the active trace (a serve
        # request / task span); untraced steps pay one thread-local read.
        # PhaseRecorder: stamped under the lock, emitted after release.
        from ray_tpu.util import tracing

        rec = tracing.PhaseRecorder()
        # per-engine rate limit (~5 span sets/s): a steady traced serving
        # loop must not cycle the bounded GCS task sink with per-step
        # spans — phase durations are steady-state, sampling keeps signal
        now = time.monotonic()
        traced = rec.active and now - self._last_phase_span >= 0.2
        if traced:
            self._last_phase_span = now
        # device telemetry: one attribute read + None check when disabled
        tel = self._telemetry
        tel_active = tel_free = tel_pending = 0
        with self._lock:
            self._tel_prefill_spent = 0
            before = self._emit_snapshot_locked()
            if self._pending or any(
                    r is not None and not self._decode_ready(r)
                    for r in self._slot_req):
                # admission + prefill run WITHOUT draining the in-flight
                # decode chunk: a new slot's fresh blocks are disjoint from
                # every in-flight table row (its own row was zeros → sink),
                # and prefill dispatches chain after the decode on the pool
                # dataflow.  Only a final prefill chunk (_dirty → refresh)
                # forces a drain, below.
                t_pf = time.time() if traced else 0.0
                self._admit_locked()
                self._prefill_step_locked()
                if traced:
                    rec.stamp("paged.admit_prefill", t_pf)
            chunk = self.config.decode_chunk
            # device appends per dispatch: a speculative cycle writes up
            # to k+1 positions (k drafted + the bonus slot), a plain
            # chunk writes `chunk`
            app = (self._spec_k + 1) if self._spec is not None else chunk
            if decode:
                # margin covers this dispatch plus one still in flight
                margin = app + 1 + (app if self._inflight else 0)
                active = self._ensure_decode_blocks_locked(margin)
            else:
                active = []
            if active:
                if self._dirty:
                    self._drain_locked()
                    self._refresh_mirrors_locked()
                    # the drain invalidated the ensure pass above: it
                    # advances lengths AND _trim_locked(margin=0) releases
                    # the margin blocks just reserved, so dispatching with
                    # the old `active` would scatter KV into sink block 0
                    # on any append crossing a block boundary (ADVICE r5
                    # high).  Re-run coverage from scratch — _inflight is
                    # now None, so one in-flight chunk's margin suffices.
                    active = self._ensure_decode_blocks_locked(app + 1)
                    if self._dirty:
                        # the re-run preempted someone: mirrors are stale
                        # again (no drain needed — nothing is in flight)
                        self._refresh_mirrors_locked()
                        active = [s for s in active
                                  if self._slot_req[s] is not None]
            if active:
                t_dec = time.time() if traced else 0.0
                w = _bucket_pow2(max(len(self._slot_req[s].blocks)
                                     for s in active))
                table = np.zeros((self.max_batch, w), np.int32)
                for s in active:
                    blks = self._slot_req[s].blocks
                    table[s, :len(blks)] = blks
                if self._spec is not None:
                    em_dev, acc_dev, spec_slots = self._spec_step_locked(
                        table, active)
                    prev, self._inflight = (
                        self._inflight,
                        (em_dev, active, spec_slots, acc_dev))
                else:
                    (em_dev, self._d_next, self.pool, self._d_lengths,
                     self._d_active, self._d_remaining, self._d_key) = \
                        self._decode(
                            self.params, self._d_next, self.pool,
                            jnp.asarray(table), self._d_lengths,
                            self._d_active, self._d_remaining,
                            self._d_stops, self._d_key,
                            self._d_temp, self._d_topk, chunk)
                    self._book_tp_collectives("decode", chunk)
                    prev, self._inflight = (self._inflight,
                                            (em_dev, active, (), None))
                if prev is not None:
                    # collect chunk N while chunk N+1 computes: the fence
                    # latency rides under the new dispatch.  The device is
                    # up to `app` appends ahead of the collected view.
                    self._collect_locked(prev[0], prev[1], margin=app,
                                         spec_slots=prev[2],
                                         acc_dev=prev[3])
                if traced:
                    rec.stamp("paged.decode", t_dec,
                              {"active_slots": len(active), "chunk": chunk,
                               "spec_k": self._spec_k})
            else:
                self._drain_locked()
            emitted = self._gather_emitted_locked(before)
            if tel is not None:
                # captured under the lock into locals; booked after
                # release next to rec.emit() (PhaseRecorder discipline)
                tel_active = sum(1 for r in self._slot_req
                                 if r is not None)
                tel_free = self.blocks.num_free()
                tel_pending = len(self._pending)
        rec.emit()
        if tel is not None:
            t_end = time.monotonic()
            tel.note_step(
                active_slots=tel_active, max_slots=self.max_batch,
                free_blocks=tel_free, total_blocks=self.num_blocks - 1,
                pending=tel_pending,
                prefill_spent=self._tel_prefill_spent,
                prefill_budget=self._tel_prefill_budget,
                busy_s=t_end - now, now=t_end)
        return emitted

    def _spec_step_locked(self, table, active: List[int]):
        """One speculative decode cycle: draft proposes k tokens per
        slot (k+1 small autoregressive steps), the target verifies all
        of them in ONE window forward.  Two dispatches, zero host syncs
        — the emission matrix is collected on the NEXT step exactly like
        a plain pipelined chunk.  Returns (em_dev [k+1, B], acc_dev [B]
        true acceptance counts, spec_slots).

        Slots whose requests are degraded (draft-pool exhaustion /
        per-adapter opt-out) ride the same verify program with a zeroed
        spec mask: zero acceptances, and their single emission is an
        exact plain decode sample — mixed batches need no second
        program.  A FULLY degraded batch instead falls back to the
        ordinary chunked decode program at k+1 steps (the same appends
        bound the ensure margin reserved): paying the (k+1)-wide verify
        window for one token per slot would make 'degraded' far slower
        than plain decode, the opposite of what degradation promises."""
        k = self._spec_k
        b = self.max_batch
        spec_slots = tuple(
            s for s in active
            if self._slot_req[s] is not None
            and self._slot_req[s].spec_enabled)
        if not spec_slots:
            (em_dev, self._d_next, self.pool, self._d_lengths,
             self._d_active, self._d_remaining, self._d_key) = \
                self._decode(
                    self.params, self._d_next, self.pool,
                    jnp.asarray(table), self._d_lengths, self._d_active,
                    self._d_remaining, self._d_stops, self._d_key,
                    self._d_temp, self._d_topk, k + 1)
            self._book_tp_collectives("decode", k + 1)
            return em_dev, None, ()
        # the draft table reuses the TARGET table's bucketed width:
        # block counts track each other (same ensure/trim formulas),
        # and one shared width means one propose compile per verify
        # bucket — warmup() covers both with a single shape grid
        dtable = np.zeros((b, table.shape[1]), np.int32)
        for s in spec_slots:
            blks = self._slot_req[s].draft_blocks
            dtable[s, :len(blks)] = blks
        (drafted, qdist, self._draft_pool, self._d_key) = \
            self._draft_propose(
                self._draft_params, self._d_next, self._draft_pool,
                jnp.asarray(dtable), self._d_lengths, self._d_key,
                self._d_temp, self._d_topk)
        (em_dev, acc_dev, self._d_next, self.pool, self._d_lengths,
         self._d_active, self._d_remaining, self._d_key) = \
            self._spec_verify(
                self.params, self._d_next, drafted, qdist, self.pool,
                jnp.asarray(table), self._d_lengths, self._d_active,
                self._d_remaining, self._d_stops, self._d_key,
                self._d_temp, self._d_topk, self._d_spec)
        self._book_tp_collectives("verify")
        return em_dev, acc_dev, spec_slots

    def flush(self) -> Dict[int, List[int]]:
        """Collect any in-flight decode chunk and return its tokens."""
        with self._lock:
            before = self._emit_snapshot_locked()
            self._drain_locked()
            return self._gather_emitted_locked(before)

    def cancel_request(self, request_id: int) -> bool:
        """Abort a live request and return its slot + blocks to the pool
        NOW (a disconnected streaming client must not keep decoding to
        max_new_tokens for nobody).  Safe at any lifecycle point: queued,
        mid-prefill, or decode-active.  Returns False if the request
        already finished (or never existed)."""
        from ray_tpu._private import flight_recorder

        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                return False
            del self._requests[request_id]
            if req in self._pending:
                try:
                    self._pending.remove(req)
                except ValueError:
                    pass
            elif req.slot >= 0:
                # the in-flight decode chunk may still WRITE blocks this
                # request owns — never free them under it (the same
                # argument as preemption's drain)
                if self._inflight is not None:
                    self._drain_locked()
                if req.slot >= 0 and self._slot_req[req.slot] is req:
                    self._free_slot_locked(req)
            req.done = True
            flight_recorder.record("request", self.slo_label or "paged",
                                   (request_id, "cancel"))
            return True

    # -- disaggregated prefill/decode handoff ---------------------------

    def export_request(self, request_id: int) -> Dict:
        """Export a request's live KV blocks + emitted-token history and
        release its slot.  Two callers: the prefill stage of a
        disaggregated deployment (export right after prefill, history is
        the single first token) and live KV migration (export mid-decode:
        the in-flight chunk is drained first — the same argument as
        ``cancel_request`` — and the handoff carries everything the
        destination needs to resume at the exact position).  The
        request's registered prompt blocks stay revivable in this
        engine's prefix cache, so the source keeps serving chain hits
        for the prompt it just handed off.

        Returns {prompt, first_token, k, v, block_size, emitted, gen}:
        k/v are host arrays [L, nblocks, block_size, kv_dim] covering
        exactly the live positions (prompt + generated-so-far), emitted
        is the full output-token history, and gen carries the sampling /
        stop / budget state.  Raises if the request isn't in the
        exportable state (prefill incomplete, or already finished — a
        1-token budget completes on the first emit and frees its partial
        block).

        Tensor-parallel engines export the same payload: the gather
        below reads the kv-head-sharded pool and ``np.asarray`` on the
        result assembles the FULL logical blocks on host (an all-gather
        over the mesh, paid once per handoff, not per step).  The
        handoff is therefore geometry-invariant — k/v carry no trace of
        the source's TP degree, so single↔sharded and 2-way↔4-way
        migrations all interoperate; the importer re-shards on entry."""
        with self._lock:
            self._drain_locked()  # resolve the in-flight chunk's tokens
            req = self._requests.get(request_id)
            if req is None or req.done or req.slot < 0:
                raise KeyError(
                    f"request {request_id} is not exportable (finished or "
                    "unknown — use max_new_tokens >= 2 for prefill-stage "
                    "requests)")
            if req.prefill_pos < len(req.prompt):
                raise RuntimeError(
                    f"request {request_id} prefill incomplete "
                    f"({req.prefill_pos}/{len(req.prompt)})")
            if not req.out_tokens:
                raise RuntimeError(
                    f"request {request_id} first token unresolved")
            # the KV pool covers positions 0..lengths-1; mid-decode the
            # block list may run ahead of that (decode_block_margin), so
            # export only the live cover — the destination re-validates
            # against the same formula
            live = int(self._lengths[req.slot])
            nb_live = max(1, math.ceil(live / self.bs))
            blocks = list(req.blocks)[:nb_live]
            barr = jnp.asarray(np.asarray(blocks, np.int32))
            # one gather program + readback; [L, nb, bs, D]
            k = np.asarray(self.pool["k"][:, barr])
            v = np.asarray(self.pool["v"][:, barr])
            g = req.gen
            out = {"prompt": list(req.prompt),
                   "first_token": int(req.out_tokens[0]),
                   "k": k, "v": v, "block_size": self.bs,
                   "emitted": [int(t) for t in req.out_tokens],
                   "gen": {"max_new_tokens": g.max_new_tokens,
                           "temperature": g.temperature,
                           "top_k": g.top_k, "seed": g.seed,
                           "stop_token_ids": list(g.stop_token_ids)}}
            req.done = True
            self._free_slot_locked(req)
            del self._requests[request_id]
            return out

    def import_request(self, prompt: Sequence[int], first_token: int,
                       k, v, gen: Optional[GenerationConfig] = None,
                       emitted: Optional[Sequence[int]] = None):
        """Admit a request directly into the decode state from handed-off
        KV: allocates pool blocks, scatters the KV in, registers the
        prompt's chain for prefix sharing, and resumes decode.  Two
        callers: the decode stage of a disaggregated deployment
        (``emitted`` omitted — ``first_token`` is emitted as the
        request's first output token) and live KV migration (``emitted``
        is the source's full output history — decode resumes at position
        prompt+len(emitted)-1 and the history is NOT re-emitted, the
        source already streamed it).

        Returns {request_id, emitted, done} or None when no slot/blocks
        are free right now — the caller falls back to a plain
        ``add_request`` (recompute; the prefix cache usually absorbs most
        of it).  Never queues: a queued import would pin host copies of
        KV that recompute could regenerate.

        On a tensor-parallel engine the scatter program writes into the
        kv-head-sharded pool, so the full-logical host blocks from
        ``export_request`` are re-sharded on entry — each device keeps
        only its kv-head slice.  Because the exported payload is
        geometry-invariant, a mixed fleet (single-device prefill tier,
        sharded decode tier, or rebalancing between TP degrees) hands
        off without a resharding step in between; when this engine has
        no free slot/blocks the usual None → ``add_request`` recompute
        fallback applies unchanged, so mixed handoff never drops a
        request."""
        gen = gen or GenerationConfig()
        plen = len(prompt)
        if plen == 0:
            raise ValueError("empty prompt")
        if emitted is not None and not emitted:
            raise ValueError("emitted history must hold >= 1 token")
        resume = emitted is not None
        hist = [int(t) for t in emitted] if resume else [int(first_token)]
        # live positions covered by the handoff KV: prompt plus every
        # emitted token except the last (whose KV is written by the NEXT
        # decode step, exactly as in the monolithic flow)
        live = plen + len(hist) - 1
        if plen + gen.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({gen.max_new_tokens})"
                f" exceeds max_seq_len {self.max_seq}")
        nb = int(k.shape[1])
        if nb != max(1, math.ceil(live / self.bs)):
            raise ValueError(
                f"handoff covers {nb} blocks but {live} live tokens "
                f"need {max(1, math.ceil(live / self.bs))} at block_size "
                f"{self.bs}")
        with self._lock:
            slot = next((s for s in range(self.max_batch)
                         if self._slot_req[s] is None), None)
            if slot is None:
                return None
            blocks = self.blocks.alloc(nb)
            if blocks is None:
                return None
            pad = _bucket_pow2(nb)
            kd = self.pool["k"].dtype
            idx = np.zeros(pad, np.int32)
            idx[:nb] = blocks  # pad rows scatter into sink block 0
            kp = np.zeros((k.shape[0], pad) + tuple(k.shape[2:]), dtype=kd)
            vp = np.zeros_like(kp)
            kp[:, :nb] = np.asarray(k, dtype=kd)
            vp[:, :nb] = np.asarray(v, dtype=kd)
            self.pool = self._import_blocks(
                self.pool, jnp.asarray(idx), jnp.asarray(kp),
                jnp.asarray(vp))
            self._req_counter += 1
            req = _PagedReq(self._req_counter, list(prompt), gen)
            req.slot = slot
            req.blocks = list(blocks)
            req.prefill_pos = plen
            self._admit_counter += 1
            req.admitted_order = self._admit_counter
            self._requests[req.request_id] = req
            self._slot_req[slot] = req
            self.blocks.register(req.prompt, req.blocks)
            self._lengths[slot] = live
            # seed the DRAFT model's KV for the handed-off prefix by
            # recomputing it at draft size (the handoff carries only the
            # target's KV — draft layers/dims differ, so there is nothing
            # to scatter).  Without this, every disagg handoff would
            # decode at acceptance-rate ~0: the draft's attention span
            # over the prompt would be garbage.  Chunked like ordinary
            # draft prefill; draft-pool exhaustion degrades to plain
            # decode exactly as elsewhere.  A mid-decode migration
            # re-seeds over prompt + history so the draft covers every
            # live position, not just the prompt.
            if self._spec is not None:
                req.spec_enabled = True
                dseq = list(prompt) + hist[:-1]
                dcover = _prefill_plan(len(dseq), 0,
                                       self.config.prefill_chunk, self.bs)
                dfresh = self.draft_blocks.alloc(dcover + 1)
                if dfresh is None:
                    req.spec_enabled = False
                else:
                    req.draft_blocks = dfresh
                    while req.draft_prefill_pos < len(dseq):
                        self._draft_prefill_chunk_locked(req, seq=dseq)
            self._next_tok[slot] = hist[-1]
            self._slot_temp[slot] = gen.temperature
            self._slot_topk[slot] = gen.top_k
            self._dirty = True
            # the source sampled these tokens; they count toward the
            # output budget exactly as in the monolithic flow.  The
            # history prefix is pre-seeded WITHOUT emission (a resumed
            # stream's client already has it); only the last token runs
            # the emit/done transition.
            req.out_tokens = hist[:-1]
            self._emit_locked(req, hist[-1])
            return {"request_id": req.request_id,
                    "emitted": [] if resume else [int(first_token)],
                    "done": req.done}

    def _emit_snapshot_locked(self) -> Dict[int, int]:
        return {id(r): len(r.out_tokens) for r in self._requests.values()}

    def _gather_emitted_locked(self, before: Dict[int, int]):
        emitted: Dict[int, List[int]] = {}
        for req in list(self._requests.values()):
            n0 = before.get(id(req), 0)
            if len(req.out_tokens) > n0:
                emitted[req.request_id] = req.out_tokens[n0:]
            if req.done:
                del self._requests[req.request_id]
        return emitted

    def _refresh_mirrors_locked(self):
        self._resolve_first_tokens_locked()  # _next_tok must be current
        decode_ready = [
            0 if (r is None or not self._decode_ready(r)) else 1
            for r in self._slot_req]
        if self._spec is not None:
            self._d_spec = jnp.asarray(np.array(
                [1 if (decode_ready[s] and r is not None and r.spec_enabled)
                 else 0
                 for s, r in enumerate(self._slot_req)], np.int32))
        self._d_next = jnp.asarray(self._next_tok)
        self._d_lengths = jnp.asarray(self._lengths)
        self._d_active = jnp.asarray(np.array(decode_ready, np.int32))
        self._d_temp = jnp.asarray(self._slot_temp)
        self._d_topk = jnp.asarray(self._slot_topk)
        remaining = np.zeros(self.max_batch, np.int32)
        stops = np.full((self.max_batch, _MAX_STOP_IDS), -1, np.int32)
        for s, r in enumerate(self._slot_req):
            if r is not None and decode_ready[s]:
                remaining[s] = r.gen.max_new_tokens - len(r.out_tokens)
                for j, sid in enumerate(r.gen.stop_token_ids):
                    stops[s, j] = sid
        self._d_remaining = jnp.asarray(remaining)
        self._d_stops = jnp.asarray(stops)
        self._dirty = False

    # -- warmup ---------------------------------------------------------

    def warmup(self, max_len: Optional[int] = None):
        """Compile the decode program for every (B, W) table bucket.

        W buckets are powers of two up to the per-sequence block cap (or
        the blocks covering ``max_len`` + pipelining margin, if given); a
        bucket transition mid-stream (a sequence crossing a pow2 block
        count) otherwise triggers a multi-second XLA compile inside the
        serving hot path — measured 4.4 s on a tunneled v5e, landing in
        every steady-state window (vLLM warms its shape buckets at
        startup for the same reason).  Uses throwaway dummy state; engine
        state is untouched."""
        b = self.max_batch
        chunk = self.config.decode_chunk
        w_cap = _bucket_pow2(self.max_blocks_per_seq)
        if max_len is not None:
            need = math.ceil((max_len + 2 * chunk + 1) / self.bs)
            w_cap = min(w_cap,
                        _bucket_pow2(min(need, self.max_blocks_per_seq)))
        key = jax.random.PRNGKey(0)
        with self._lock:
            self._drain_locked()
            w = 1
            while True:
                # donate the REAL pool and recapture it: a second full-size
                # pool would double peak HBM exactly when num_blocks is
                # sized to fill it.  All-zero tables + active=0 mean every
                # warmup write lands in sink block 0 (garbage by design).
                if self._spec is not None:
                    # speculative serving dispatches verify (per target-
                    # table bucket) + propose (per draft-table bucket),
                    # never the chunked decode program — warm what runs
                    k, v = self._spec_k, self.cfg.vocab_size
                    out = self._spec_verify(
                        self.params, jnp.zeros(b, jnp.int32),
                        jnp.zeros((k, b), jnp.int32),
                        jnp.zeros((k, b, v), jnp.float32), self.pool,
                        jnp.zeros((b, w), jnp.int32),
                        jnp.zeros(b, jnp.int32), jnp.zeros(b, jnp.int32),
                        jnp.zeros(b, jnp.int32),
                        jnp.full((b, _MAX_STOP_IDS), -1, jnp.int32), key,
                        jnp.zeros(b, jnp.float32), jnp.zeros(b, jnp.int32),
                        jnp.zeros(b, jnp.int32))
                    self.pool = out[3]  # (emitted, accepted, tokens, pool..)
                    np.asarray(out[0])
                    pout = self._draft_propose(
                        self._draft_params, jnp.zeros(b, jnp.int32),
                        self._draft_pool, jnp.zeros((b, w), jnp.int32),
                        jnp.zeros(b, jnp.int32), key,
                        jnp.zeros(b, jnp.float32), jnp.zeros(b, jnp.int32))
                    self._draft_pool = pout[2]
                    np.asarray(pout[0])
                    # fully-degraded fallback: chunked decode at k+1
                    # steps — a mid-serve degrade must not compile
                    dout = self._decode(
                        self.params, jnp.zeros(b, jnp.int32), self.pool,
                        jnp.zeros((b, w), jnp.int32),
                        jnp.zeros(b, jnp.int32), jnp.zeros(b, jnp.int32),
                        jnp.zeros(b, jnp.int32),
                        jnp.full((b, _MAX_STOP_IDS), -1, jnp.int32), key,
                        jnp.zeros(b, jnp.float32), jnp.zeros(b, jnp.int32),
                        k + 1)
                    self.pool = dout[2]
                    np.asarray(dout[0])
                else:
                    out = self._decode(
                        self.params, jnp.zeros(b, jnp.int32), self.pool,
                        jnp.zeros((b, w), jnp.int32),
                        jnp.zeros(b, jnp.int32),
                        jnp.zeros(b, jnp.int32), jnp.zeros(b, jnp.int32),
                        jnp.full((b, _MAX_STOP_IDS), -1, jnp.int32), key,
                        jnp.zeros(b, jnp.float32), jnp.zeros(b, jnp.int32),
                        chunk)
                    self.pool = out[2]
                    np.asarray(out[0])  # force compile + run to completion
                if w >= w_cap:
                    break
                w *= 2
            # prefill programs: one per pow2 chunk width (table width is
            # fixed), so this covers EVERY prefill shape serving can hit.
            # Serving caps chunks at the bucketed max prompt width AND the
            # fixed table's coverage — warm only reachable widths.
            c_cap = min(self.config.prefill_chunk,
                        self._prefill_w * self.bs,
                        _bucket_pow2(_pad_to(self.max_seq, self.bs),
                                     lo=self.bs))
            c = self.bs
            while True:
                c = min(c, c_cap)
                ids, self.pool, _ = self._prefill_chunk(
                    self.params, jnp.zeros((1, c), jnp.int32), self.pool,
                    jnp.zeros((1, self._prefill_w), jnp.int32),
                    jnp.int32(0), jnp.int32(0), key,
                    jnp.zeros(1, jnp.float32), jnp.zeros(1, jnp.int32))
                np.asarray(ids)
                if self._spec is not None:
                    self._draft_pool = self._draft_prefill(
                        self._draft_params, jnp.zeros((1, c), jnp.int32),
                        self._draft_pool,
                        jnp.zeros((1, self._prefill_w), jnp.int32),
                        jnp.int32(0))
                if c >= c_cap:
                    break
                c *= 2

    # -- sync convenience ----------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]],
                 gen: Optional[GenerationConfig] = None) -> List[List[int]]:
        ids = [self.add_request(p, gen) for p in prompts]
        results: Dict[int, List[int]] = {i: [] for i in ids}
        waiting = set(ids)
        while waiting and self.has_work():
            emitted = self.step()
            for rid, toks in emitted.items():
                if rid in results:
                    results[rid].extend(toks)
            with self._lock:
                waiting = {rid for rid in waiting if rid in self._requests}
        # the last booking step may have dispatched one more (all-inactive)
        # chunk: collect it so has_work() is False on a drained engine
        self.flush()
        return [results[i] for i in ids]


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m
