"""LLM configs.

reference: python/ray/llm/_internal (LLMConfig, engine config). The
reference reads TP/PP degrees out of vLLM engine_kwargs
(serve/deployments/llm/vllm/vllm_models.py:177-186); here the engine is the
framework's own JAX engine and the degrees are mesh axes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class GenerationConfig:
    max_new_tokens: int = 64
    temperature: float = 0.0  # 0 → greedy
    top_k: int = 0  # 0 → disabled
    seed: int = 0
    stop_token_ids: tuple = ()


@dataclasses.dataclass
class SpeculativeConfig:
    """Draft-model speculative decoding (paged engine only).

    A small draft model proposes ``num_speculative_tokens`` tokens per
    slot per step; the target model batch-verifies all of them in ONE
    forward pass (standard rejection sampling at temperature > 0; exact
    longest-agreeing-prefix at temperature 0 — greedy output is
    bit-identical to non-speculative decode).  The draft's KV lives in
    its own block pool sharing the BlockManager machinery; draft-pool
    exhaustion degrades the affected request to non-speculative decode
    (zero drops).
    """

    # a models.llama.LlamaConfig for the draft model (same vocab as the
    # target; typically far fewer layers / smaller dim)
    draft_model_config: Any = None
    # k: drafted tokens verified per target forward (per slot per step).
    # Each step emits between 1 (all rejected) and k+1 (all accepted +
    # the bonus token) tokens per slot.
    num_speculative_tokens: int = 4
    # draft KV pool size in blocks; None → the target pool's block count
    # (draft blocks are much smaller — draft layers/kv dims)
    draft_num_blocks: Optional[int] = None
    # multi-LoRA extension: per-adapter draft choice.  Maps a serve
    # model id to overrides applied when that adapter's engine is built:
    #   {"enabled": False}              — this adapter decodes without
    #                                     speculation
    #   {"num_speculative_tokens": k}   — per-adapter k
    #   {"draft_adapter": <lora tree>}  — a LoRA adapter (llm/lora.py)
    #                                     merged into the DRAFT model for
    #                                     this id (draft tracks the tuned
    #                                     target, keeping acceptance up)
    per_adapter: Optional[Dict[str, Dict[str, Any]]] = None


@dataclasses.dataclass
class LLMConfig:
    """reference analog: llm/_internal LLMConfig + vLLM engine_kwargs."""

    model_config: Any = None  # a models.llama.LlamaConfig (or compatible)
    max_batch_size: int = 8
    # tokens decoded per dispatch (multi-step scheduling): the whole chunk
    # runs as ONE device program with stop/budget handling in-program, so
    # per-dispatch host latency is amortized over `decode_chunk` tokens.
    # 1 = sync every token (lowest streaming latency).
    decode_chunk: int = 8
    max_seq_len: Optional[int] = None  # default: model_config.max_seq_len
    # --- KV cache layout (reference capability boundary: paged attention /
    # chunked prefill / prefix caching come from vLLM engine_kwargs,
    # vllm_models.py:177-186; here the engine provides them natively) ---
    # "paged": block-pool cache, HBM ∝ actual request lengths, memory-based
    # admission, chunked prefill, prefix caching. "static": per-slot
    # max_seq_len cache (lowest bookkeeping overhead for tiny batches).
    kv_cache: str = "paged"
    block_size: int = 16
    # pool size in blocks; None → half the HBM the static cache would use
    num_blocks: Optional[int] = None
    # prompt tokens prefilled per step (multiple of block_size); long
    # prompts interleave with decode instead of stalling it
    prefill_chunk: int = 256
    # prompt tokens the engine may prefill per STEP across all slots (the
    # vLLM max_num_batched_tokens analog): chunked-prefill scheduling
    # interleaves bounded prefill chunks with decode steps under this
    # budget, so a long prompt cannot starve decode ITL inside continuous
    # batching. None = prefill_chunk (one chunk's worth). Raise for
    # burst-arrival serving: a 32-client burst otherwise ramps one chunk
    # per step, serializing admission.
    prefill_token_budget: Optional[int] = None
    # deprecated alias for prefill_token_budget (pre-ISSUE-11 name); the
    # new knob wins when both are set
    prefill_budget_tokens: Optional[int] = None
    # draft-model speculative decoding (paged engine only; see
    # SpeculativeConfig). None disables — the disabled path is untouched:
    # no draft pool, no extra device programs, no metrics booked.
    speculative_config: Optional[SpeculativeConfig] = None
    enable_prefix_caching: bool = True
    # --- tiered prefix cache (paged engine) ---
    # host-RAM tier under the HBM chain-hash pool: full prompt blocks
    # evicted from HBM under pressure demote here (one small device
    # readback per eviction) and revive without recompute on a later
    # match.  0 disables the tier ladder entirely.
    host_kv_cache_bytes: int = 64 * 1024**2
    # third tier: blocks evicted from host RAM spill to the plasma object
    # store (cluster-visible, survives engine HBM churn), capped at this
    # many blocks.  0 (default) disables; requires an initialized ray_tpu
    # worker — without one the host tier simply drops its evictions.
    plasma_kv_cache_blocks: int = 0
    # True -> the pallas TPU paged-attention kernel for decode (single-chip
    # TPU, head_dim % 128 == 0, pp == 1). None = auto: ON where supported
    # (measured v5e b32: ties the XLA block-gather at span 256, 2.2x faster
    # at span 1024 — benchmarks/paged_bisect.py). True forces it (raises
    # off-TPU); False forces the gather path; "interpret" is a test hook
    # that runs the kernel in pallas interpret mode off-TPU.
    paged_attention_kernel: Optional[Any] = None
    # parallelism degrees (mesh axes; the vllm_models.py:177-186 analog —
    # pipeline degree folded into placement sizing per vllm_models.py:181-191)
    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    # pre-built jax.sharding.Mesh override for the engine.  None (default)
    # builds one from tensor/pipeline_parallel_size over the first visible
    # devices; pass a mesh to pin WHICH devices a replica shards over
    # (e.g. a placement-group slice).  Must carry a "tensor" axis of size
    # tensor_parallel_size (and "pipeline" of pipeline_parallel_size).
    mesh: Optional[Any] = None
    # --- tensor-parallel collective routing (paged engine, tp > 1) ---
    # route the per-layer decode allreduces through the α-β collective
    # planner as EXPLICIT shard_map programs (flat psum / ring / tree
    # chosen per message size and link class, decision metered into
    # ray_tpu_collective_plan_total).  False = GSPMD's implicit psum
    # (identical numerics for flat/ring; no plan metrics, no overlap).
    tp_planned_collectives: bool = True
    # chain each planned collective through lax.optimization_barrier so
    # XLA's scheduler overlaps it with the next layer's compute (identity
    # numerics — the A/B is bit-equal; same mechanism as make_train_step's
    # bucketed gradient sync).  Only meaningful with planned collectives.
    tp_overlap_collectives: bool = True
    # force one algorithm ("flat" | "ring" | "tree") instead of planning —
    # a test/bench hook; None = plan per message size.
    tp_collective_algorithm: Optional[str] = None
    # serving
    num_replicas: int = 1
    chips_per_replica: Optional[int] = None

    def resources_per_replica(self) -> Dict[str, float]:
        chips = self.chips_per_replica
        if chips is None:
            chips = (self.tensor_parallel_size * self.pipeline_parallel_size
                     * self.data_parallel_size)
        res: Dict[str, float] = {"CPU": 1.0}
        if chips > 0 and (chips > 1 or self.chips_per_replica is not None):
            res["TPU"] = float(chips)
        return res
