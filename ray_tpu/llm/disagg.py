"""Disaggregated prefill/decode LLM serving with KV-block handoff.

The monolithic ``LLMServer`` runs prefill and decode in one engine: a burst
of long prompts competes with the running decode batch for the same chips,
and burst TTFT collapses (measured r05: p50 2.4-2.8 s at 32 SSE clients).
This module splits the two phases into separately autoscaled serve
deployments — the topology the Gemma-on-TPU serving comparison argues for
(PAPERS.md, arxiv 2605.25645):

  - ``PrefillServer``: paged engines that ONLY prefill.  A finished
    prompt's KV blocks are exported (``PagedJaxLLMEngine.export_request``)
    and handed to a decode replica; the prompt's chain stays registered in
    the prefill replica's tiered prefix cache, so repeated prefixes keep
    hitting HBM/host tiers there.
  - ``DecodeServer``: an ``LLMServer`` whose requests arrive ALREADY
    prefilled — ``import_request`` scatters the handed-off blocks into its
    pool and the request joins the continuous decode batch with zero
    prompt compute.  If the import cannot be admitted right now (no
    slot/blocks), it falls back to ordinary ``add_request`` recompute —
    the prefix cache absorbs most of the cost, and no request is dropped.
  - ``DisaggLLMServer``: the lightweight ingress coordinating the two;
    its prefill handle routes cache-aware (serve/handle.py reads the
    per-replica prefix digests), so a warm prefix lands on the replica
    already holding the chain.

KV handoff rides either the plain actor-call payload path (``transport=
"object"`` — plasma/inline, works everywhere) or the device-tensor channel
plane (``transport="channel"`` — XlaTensorChannel ICI p2p on TPU, the
store communicator off-TPU; arrays never transit the GCS), optionally
int8-quantized with the PR 3 codec (``handoff_compression="int8"``,
lossy opt-in).  Both legs are metered as ``ray_tpu_kv_handoff_*``; the
100k-GPU collectives paper (arxiv 2510.20171) is the argument for keeping
this traffic on the transfer plane instead of the control plane.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.llm.config import GenerationConfig, LLMConfig
from ray_tpu.llm.serve import LLMServer, _jax_backend

_HANDOFF_TIMEOUT_S = 600.0  # covers first-request jit compiles


class PrefillServer:
    """Prefill-only deployment: drives ``step(decode=False)`` and exports
    finished prompts' KV blocks.  Concurrent requests interleave their
    prefill chunks through the engine's own admission/budget machinery
    (one step advances every mid-prefill slot under the prefill token
    budget), exactly as in the monolithic engine — there is just no decode
    batch competing for the dispatch queue."""

    def __init__(self, llm_config: LLMConfig, params=None):
        import dataclasses

        from ray_tpu.llm.engine import make_engine

        if llm_config.kv_cache != "paged":
            raise ValueError("disaggregated serving requires kv_cache='paged'")
        if llm_config.speculative_config is not None:
            # prefill never decodes: a draft pool here would burn HBM and
            # every prompt would pay a pointless draft prefill.  The
            # DECODE stage is the speculative consumer — import_request
            # seeds its draft KV by recompute at draft size.
            llm_config = dataclasses.replace(llm_config,
                                             speculative_config=None)
        self._config = llm_config
        self._engine = make_engine(llm_config, params)
        if hasattr(self._engine, "warmup") and _jax_backend() == "tpu":
            self._engine.warmup()
        self._inflight = 0
        self._lock = threading.Lock()

    def set_slo_label(self, name: str) -> None:
        """Serving SLO threading (serve/_private/replica.py): engine-side
        lifecycle stages book under the prefill deployment's name."""
        try:
            self._engine.slo_label = name
        except Exception:  # noqa: BLE001 — engine variants without SLO threading are legal
            pass

    def prefix_digest(self) -> Dict[str, Any]:
        digest = self._engine.prefix_digest()
        digest["models"] = []
        digest["qlen"] = self._inflight
        return digest

    def utilization(self) -> Optional[Dict[str, Any]]:
        """Device-telemetry row (replica publish / state.utilization())."""
        util = getattr(self._engine, "utilization", None)
        return util() if util is not None else None

    def queue_depth(self) -> int:
        return self._inflight

    def _track(self, delta: int):
        from ray_tpu._private import runtime_metrics

        with self._lock:
            self._inflight += delta
            n = self._inflight
        runtime_metrics.set_disagg_queue_depth("prefill", n)

    def prefill(self, prompt: Sequence[int], max_new_tokens: int = 64,
                temperature: float = 0.0, top_k: int = 0,
                stop_token_ids: Sequence[int] = (),
                handoff_channel=None) -> Dict[str, Any]:
        """Prefill one prompt and export its KV + first sampled token.

        Returns the handoff descriptor; with ``handoff_channel`` the k/v
        arrays are written to the channel (off-thread — the descriptor
        returns immediately so the decode side can start reading) and the
        descriptor carries only shapes."""
        from ray_tpu._private import runtime_metrics

        eng = self._engine
        # the real token budget is enforced by the decode stage; prefill
        # only needs the request alive past its first emit (>= 2), while
        # still respecting the pool's max_seq admission check
        gen = GenerationConfig(
            max_new_tokens=max(2, min(int(max_new_tokens),
                                      eng.max_seq - len(prompt))),
            temperature=temperature, top_k=top_k,
            stop_token_ids=tuple(stop_token_ids))
        # the handoff latency metric covers export gather + transfer
        # enqueue only — NOT the prefill compute (nor a first-request jit
        # compile), which would swamp it by orders of magnitude
        t0 = time.perf_counter()
        self._track(1)
        try:
            rid = eng.add_request(list(prompt), gen)
            deadline = time.monotonic() + _HANDOFF_TIMEOUT_S
            while True:
                eng.step(decode=False)
                with eng._lock:
                    req = eng._requests.get(rid)
                    ready = (req is not None and req.slot >= 0
                             and req.prefill_pos >= len(req.prompt)
                             and req.out_tokens)
                    gone = req is None
                if ready:
                    break
                if gone:
                    raise RuntimeError(
                        "prefill request finished before export (1-token "
                        "budget near max_seq) — decode will recompute")
                if time.monotonic() > deadline:
                    raise TimeoutError("prefill timed out")
                if not eng.has_work():
                    time.sleep(0.001)
            t0 = time.perf_counter()
            handoff = eng.export_request(rid)
        except (RuntimeError, ValueError):
            # graceful degradation: hand off the prompt with no KV — the
            # decode stage recomputes (its prefix cache usually helps; a
            # genuinely invalid request raises the same error there)
            handoff = {"prompt": list(prompt), "first_token": None,
                       "k": None, "v": None,
                       "block_size": self._config.block_size}
        finally:
            self._track(-1)
        # sender legs book latency only (nbytes=0) under a distinct
        # "<transport>_export" tag: the receiver is the one place that
        # knows the true moved size for every transport (wire codes+scales
        # when quantized), so the plain transport tag counts each handoff
        # exactly once — bytes, handoff count and effective bandwidth all
        # read off the receiver leg even when both stages share a process
        if handoff_channel is not None and handoff.get("k") is not None:
            k, v = handoff.pop("k"), handoff.pop("v")
            spec = getattr(handoff_channel, "_compression", None)
            transport = "channel_int8" if spec is not None else "channel"

            def _write():
                try:
                    handoff_channel.write((k, v),
                                          timeout=_HANDOFF_TIMEOUT_S)
                except Exception:  # noqa: BLE001 — reader gone: drop
                    pass

            # off-thread: channel writes rendezvous with the reader, and
            # the reader only starts once this call returns the descriptor
            threading.Thread(target=_write, daemon=True,
                             name="kv-handoff-write").start()
            handoff["via_channel"] = True
            runtime_metrics.record_kv_handoff(
                transport + "_export", 0, time.perf_counter() - t0)
        elif handoff.get("k") is not None:
            runtime_metrics.record_kv_handoff(
                "object_export", 0, time.perf_counter() - t0)
        return handoff

    def check_health(self) -> bool:
        return True


class DecodeServer(LLMServer):
    """Decode stage: an ``LLMServer`` (engine loop, waiters, LoRA LRU)
    whose requests normally arrive as KV handoffs instead of prompts."""

    def _import_handoff(self, handoff: Dict[str, Any],
                        gen: GenerationConfig):
        """Admit a handoff into the base engine; returns the waiter key.
        Falls back to plain add_request (recompute) when the handoff has
        no KV or cannot be admitted right now."""
        from ray_tpu._private import runtime_metrics

        eng = self._engine
        t0 = time.perf_counter()
        k, v = handoff.get("k"), handoff.get("v")
        chan = handoff.get("channel")
        transport = "object"
        if chan is not None and handoff.get("via_channel"):
            spec = getattr(chan, "_compression", None)
            transport = "channel_int8" if spec is not None else "channel"
            try:
                chan.register_reader(0)
            except Exception:  # noqa: BLE001 — reader already registered
                pass           # by a prior handoff on this channel
            try:
                k, v = chan.read(timeout=_HANDOFF_TIMEOUT_S)
            except Exception:  # noqa: BLE001 — lost channel: recompute
                k = v = None
        res = None
        if k is not None and handoff.get("first_token") is not None:
            try:
                res = eng.import_request(handoff["prompt"],
                                         handoff["first_token"], k, v, gen)
            except ValueError:
                # shape mismatch (per-stage config overrides: different
                # block_size / smaller decode max_seq): the handoff KV is
                # unusable here — recompute; a request that is genuinely
                # invalid for THIS engine raises the same error from
                # add_request below
                res = None
        if res is None:
            # recompute path: zero drops even when the pool is full or the
            # handoff was degraded — continuous batching absorbs it
            rid = eng.add_request(list(handoff["prompt"]), gen)
            self._set_decode_depth()
            return (None, 0, rid)
        # channel legs meter the WIRE bytes (int8 codes + scales when
        # quantized), not the logical array size
        nbytes = (chan.last_read_nbytes
                  if (chan is not None and transport.startswith("channel"))
                  else (k.nbytes + v.nbytes))
        handoff_s = time.perf_counter() - t0
        runtime_metrics.record_kv_handoff(transport, nbytes, handoff_s)
        # lifecycle stage under the decode deployment's label (the receiver
        # leg is the authoritative per-handoff observation, matching the
        # kv_handoff metric convention)
        from ray_tpu.serve._private import slo

        slo.record_stage(self._slo_label, "handoff", handoff_s)
        wkey = (None, 0, res["request_id"])
        # seed the waiter with the prefill-sampled first token: the engine
        # emitted it before the loop's next snapshot, so the loop alone
        # would never deliver it.  PREPENDED, not appended — between
        # import_request releasing the engine lock and this block, the
        # _run loop may already have stepped the engine and buffered token
        # #2 (or finished the request and moved its buffer to _done);
        # appending would deliver tokens out of order / strand the first
        # token in a leaked _waiters entry
        with self._cv:
            self._active_waiters.add(wkey)
            if res["done"] or wkey in self._done:
                self._done.setdefault(wkey, [])[:0] = res["emitted"]
            else:
                self._waiters.setdefault(wkey, [])[:0] = res["emitted"]
            self._cv.notify_all()
        self._set_decode_depth()
        return wkey

    def _set_decode_depth(self):
        from ray_tpu._private import runtime_metrics

        try:
            with self._engine._lock:
                n = len(self._engine._requests)
            runtime_metrics.set_disagg_queue_depth("decode", n)
        except Exception:  # noqa: BLE001 — depth gauge is telemetry; engine may be mid-swap
            pass

    @staticmethod
    def _gen_of(max_new_tokens, temperature, top_k, stop_token_ids):
        return GenerationConfig(max_new_tokens=max_new_tokens,
                                temperature=temperature, top_k=top_k,
                                stop_token_ids=tuple(stop_token_ids))

    def decode_from_handoff(self, handoff: Dict[str, Any],
                            max_new_tokens: int = 64,
                            temperature: float = 0.0, top_k: int = 0,
                            stop_token_ids: Sequence[int] = ()) -> List[int]:
        wkey = self._import_handoff(
            handoff, self._gen_of(max_new_tokens, temperature, top_k,
                                  stop_token_ids))
        return self._wait_done(wkey)

    def decode_stream_from_handoff(self, handoff: Dict[str, Any],
                                   max_new_tokens: int = 64,
                                   temperature: float = 0.0, top_k: int = 0,
                                   stop_token_ids: Sequence[int] = ()):
        wkey = self._import_handoff(
            handoff, self._gen_of(max_new_tokens, temperature, top_k,
                                  stop_token_ids))
        yield from self._iter_tokens(wkey)


class DisaggLLMServer:
    """Ingress of the disaggregated topology: prefill handle (cache-aware
    routed) -> KV handoff -> decode handle.  LoRA requests (``model=``)
    bypass disaggregation and run monolithically on the decode stage —
    adapter engines live there."""

    def __init__(self, llm_config: LLMConfig, prefill_handle, decode_handle,
                 transport: str = "object", handoff_compression=None):
        if transport not in ("object", "channel"):
            raise ValueError(f"transport must be 'object' or 'channel' "
                             f"(got {transport!r})")
        self._config = llm_config
        self._prefill = prefill_handle
        self._decode = decode_handle
        self._transport = transport
        self._compression = handoff_compression
        self._slo_label: Optional[str] = None

    def set_slo_label(self, name: str) -> None:
        self._slo_label = name

    def _make_channel(self):
        from ray_tpu.experimental.channel.xla_tensor_channel import (
            XlaTensorChannel,
        )

        return XlaTensorChannel(f"kvh-{uuid.uuid4().hex[:12]}",
                                compression=self._compression)

    def _run_prefill(self, prompt, gen_kwargs):
        chan = self._make_channel() if self._transport == "channel" else None
        resp = self._prefill.prefill.remote(
            prompt=list(prompt), handoff_channel=chan, **gen_kwargs)
        handoff = resp.result(timeout_s=_HANDOFF_TIMEOUT_S)
        if chan is not None:
            handoff["channel"] = chan
        return handoff

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k: int = 0,
                 stop_token_ids: Sequence[int] = (),
                 model: Optional[str] = None) -> List[int]:
        gen_kwargs = dict(max_new_tokens=max_new_tokens,
                          temperature=temperature, top_k=top_k,
                          stop_token_ids=tuple(stop_token_ids))
        if model:
            return self._decode.generate.remote(
                prompt=list(prompt), model=model,
                **gen_kwargs).result(timeout_s=_HANDOFF_TIMEOUT_S)
        handoff = self._run_prefill(prompt, gen_kwargs)
        return self._decode.decode_from_handoff.remote(
            handoff, **gen_kwargs).result(timeout_s=_HANDOFF_TIMEOUT_S)

    def generate_stream(self, prompt: Sequence[int],
                        max_new_tokens: int = 64, temperature: float = 0.0,
                        top_k: int = 0, stop_token_ids: Sequence[int] = (),
                        model: Optional[str] = None):
        gen_kwargs = dict(max_new_tokens=max_new_tokens,
                          temperature=temperature, top_k=top_k,
                          stop_token_ids=tuple(stop_token_ids))
        if model:
            gen = self._decode.options(stream=True).generate_stream.remote(
                prompt=list(prompt), model=model, **gen_kwargs)
        else:
            handoff = self._run_prefill(prompt, gen_kwargs)
            gen = self._decode.options(
                stream=True).decode_stream_from_handoff.remote(
                    handoff, **gen_kwargs)
        for chunk in gen:
            yield chunk

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Same dict API as ``LLMServer.__call__``."""
        toks = self.generate(
            request["prompt"],
            max_new_tokens=request.get("max_new_tokens", 64),
            temperature=request.get("temperature", 0.0),
            top_k=request.get("top_k", 0),
            stop_token_ids=request.get("stop_token_ids", ()),
            model=request.get("model"),
        )
        return {"tokens": toks}

    def check_health(self) -> bool:
        return True


def build_disagg_llm_deployment(
        llm_config: LLMConfig, params=None, *, name: str = "llm",
        prefill_replicas: int = 1, decode_replicas: int = 1,
        transport: str = "object", handoff_compression=None,
        prefill_config: Optional[LLMConfig] = None,
        decode_config: Optional[LLMConfig] = None,
        prefill_autoscaling: Optional[dict] = None,
        decode_autoscaling: Optional[dict] = None,
        lora_adapters: Optional[Dict[str, Any]] = None,
        draft_params=None):
    """An Application serving ``llm_config`` as separately autoscaled
    prefill and decode deployments behind one ingress (the disaggregated
    analog of ``build_llm_deployment``).  ``prefill_config`` /
    ``decode_config`` override the per-stage engine shapes (a prefill pool
    mostly needs prompt-sized residency; decode wants the full pool);
    ``*_autoscaling`` are the standard serve autoscaling_config dicts, so
    the controller scales each stage on its own queue depth.

    With ``llm_config.speculative_config`` set, the DECODE stage is the
    speculative consumer (``draft_params`` feeds its draft model; every
    imported handoff seeds the draft KV by recompute at draft size); the
    prefill stage strips speculation — it never decodes."""
    from ray_tpu import serve

    pre_cfg = prefill_config or llm_config
    dec_cfg = decode_config or llm_config
    prefill_app = serve.deployment(
        PrefillServer, name=f"{name}-prefill",
        num_replicas=prefill_replicas,
        max_ongoing_requests=max(8, pre_cfg.max_batch_size),
        autoscaling_config=prefill_autoscaling,
        ray_actor_options={"resources": pre_cfg.resources_per_replica()},
    ).bind(pre_cfg, params)
    decode_app = serve.deployment(
        DecodeServer, name=f"{name}-decode",
        num_replicas=decode_replicas,
        max_ongoing_requests=max(8, dec_cfg.max_batch_size),
        autoscaling_config=decode_autoscaling,
        ray_actor_options={"resources": dec_cfg.resources_per_replica()},
    ).bind(dec_cfg, params, lora_adapters, draft_params)
    ingress = serve.deployment(
        DisaggLLMServer, name=name, num_replicas=1,
        max_ongoing_requests=4 * max(8, dec_cfg.max_batch_size),
        ray_actor_options={"resources": {"CPU": 0.1}},
    ).bind(llm_config, prefill_app, decode_app,
           transport=transport, handoff_compression=handoff_compression)
    return ingress
