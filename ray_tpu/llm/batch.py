"""Batch LLM inference over datasets: Processor + stages.

reference: python/ray/llm/_internal/batch/processor/ + stages/ — a
Processor turns a Dataset through preprocess -> engine inference ->
postprocess stages, with the engine stage running on an autoscaling actor
pool (one engine per actor, chips bound via the "TPU" resource).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

from ray_tpu.llm.config import GenerationConfig, LLMConfig


@dataclasses.dataclass
class ProcessorConfig:
    """reference analog: batch/processor config (concurrency + batch size)."""

    llm_config: LLMConfig = None
    batch_size: int = 8
    concurrency: int = 1
    max_new_tokens: int = 32
    temperature: float = 0.0


class _EngineStage:
    """Actor-pool stage: owns one JaxLLMEngine, maps prompt batches."""

    def __init__(self, llm_config: LLMConfig, max_new_tokens: int,
                 temperature: float):
        from ray_tpu.llm.engine import make_engine

        self._engine = make_engine(llm_config)
        self._gen = GenerationConfig(max_new_tokens=max_new_tokens,
                                     temperature=temperature)

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        prompts = [list(p) for p in batch["prompt_tokens"]]
        outs = self._engine.generate(prompts, self._gen)
        out = dict(batch)
        out["generated_tokens"] = outs
        return out


class Processor:
    """``processor(dataset) -> dataset`` (reference: batch/processor/base).

    Stages: optional row-wise preprocess -> engine map_batches on an actor
    pool -> optional row-wise postprocess.
    """

    def __init__(self, config: ProcessorConfig,
                 preprocess: Optional[Callable[[dict], dict]] = None,
                 postprocess: Optional[Callable[[dict], dict]] = None):
        if config.llm_config is None:
            raise ValueError("ProcessorConfig.llm_config is required")
        self.config = config
        self.preprocess = preprocess
        self.postprocess = postprocess

    def __call__(self, dataset):
        from ray_tpu.data.dataset import ActorPoolStrategy

        ds = dataset
        if self.preprocess is not None:
            ds = ds.map(self.preprocess)
        ds = ds.map_batches(
            _EngineStage,
            batch_size=self.config.batch_size,
            batch_format="pydict",
            compute=ActorPoolStrategy(size=self.config.concurrency),
            fn_constructor_args=(self.config.llm_config,
                                 self.config.max_new_tokens,
                                 self.config.temperature),
            resources=self.config.llm_config.resources_per_replica(),
        )
        if self.postprocess is not None:
            ds = ds.map(self.postprocess)
        return ds


def build_llm_processor(config: ProcessorConfig,
                        preprocess: Optional[Callable] = None,
                        postprocess: Optional[Callable] = None) -> Processor:
    """reference: ray.data.llm.build_llm_processor."""
    return Processor(config, preprocess, postprocess)
