"""LLM toolkit: batch inference + serving on the framework's JAX engine.

reference: python/ray/llm/ (~20.8k LoC) — batch Processor/stages and
LLMServer deployments on vLLM.  Here the engine is framework-native:
KV-cache decode with continuous batching, jitted prefill/decode, mesh-based
parallelism degrees.  Two cache layouts behind ``make_engine``:
PagedJaxLLMEngine (block-pool KV, chunked prefill, prefix caching — the
default) and JaxLLMEngine (static per-slot cache).
"""

from ray_tpu.llm.batch import Processor, ProcessorConfig, build_llm_processor
from ray_tpu.llm.config import GenerationConfig, LLMConfig, SpeculativeConfig
from ray_tpu.llm.disagg import (
    DecodeServer,
    DisaggLLMServer,
    PrefillServer,
    build_disagg_llm_deployment,
)
from ray_tpu.llm.engine import JaxLLMEngine, make_engine
from ray_tpu.llm.paged import BlockAllocator, BlockManager, PagedJaxLLMEngine
from ray_tpu.llm.lora import LoRAConfig, LoRAManager, init_lora, merge_lora
from ray_tpu.llm.openai_api import ByteTokenizer, OpenAICompatServer, build_openai_app
from ray_tpu.llm.serve import LLMServer, build_llm_deployment

__all__ = [
    "BlockAllocator",
    "BlockManager",
    "DecodeServer",
    "DisaggLLMServer",
    "PrefillServer",
    "build_disagg_llm_deployment",
    "GenerationConfig",
    "JaxLLMEngine",
    "LLMConfig",
    "PagedJaxLLMEngine",
    "make_engine",
    "LLMServer",
    "LoRAConfig",
    "LoRAManager",
    "init_lora",
    "merge_lora",
    "Processor",
    "ProcessorConfig",
    "SpeculativeConfig",
    "build_llm_deployment",
    "build_openai_app",
    "OpenAICompatServer",
    "ByteTokenizer",
    "build_llm_processor",
]
