"""JAX LLM inference engine: KV-cache decode with continuous batching.

The reference delegates serving to vLLM and reserves matching placement
groups (reference: llm/_internal/serve/deployments/llm/vllm/vllm_models.py
:177-186, :241-259).  Here the engine itself is framework-native and
TPU-first:

  - static-shape KV cache with `max_batch` sequence slots; one jitted
    decode program advances EVERY active slot (continuous batching — new
    requests join the running batch at any step by prefilling into a free
    slot, no generation restart)
  - multi-step scheduling: each step() runs `decode_chunk` tokens as ONE
    device program (stop tokens / budgets / cache bounds handled
    in-program; slots self-deactivate mid-chunk), amortizing per-dispatch
    host latency — measured 58 -> 600 tok/s on a tunneled v5e at chunk 64
  - the decode-loop state (next tokens, lengths, active mask, budgets,
    stop ids, PRNG key) lives on DEVICE between steps; the host uploads
    mirrors only on slot transitions and reads back one [chunk, B] token
    block per step
  - prefill jitted per bucketed prompt length (powers of two) so arrival
    order doesn't cause recompiles
  - sampling (greedy / temperature / top-k) inside the jitted program;
    only sampled token ids cross the host boundary each step
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private.analysis.lock_witness import make_lock
from ray_tpu._private import device_telemetry
from ray_tpu.llm.config import GenerationConfig, LLMConfig
from ray_tpu.models import llama
from ray_tpu.ops.rope import rope_frequencies


# stop-token ids travel to the device as a fixed-width padded row per slot
_MAX_STOP_IDS = 8
# top-k sampling cap: the kth threshold comes from lax.top_k(logits, 64)
# instead of a full [B, V] sort — the sort was milliseconds per decode step
# at V=32k on TPU, the top-64 is microseconds
_MAX_TOP_K = 64


@dataclasses.dataclass
class _Request:
    request_id: int
    prompt: List[int]
    gen: GenerationConfig
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    slot: int = -1
    error: Optional[str] = None


def _masked_scaled(logits, temps, top_ks):
    """Temperature-scaled, top-k-masked logits [B, V] — the categorical
    branch's pre-softmax shape, shared by sampling and the speculative
    verifier (target/draft distributions MUST match what non-speculative
    sampling would draw from).  temps <= 0 rows divide by 1.0 (a benign
    placeholder — those rows are greedy and never read the scaled value;
    the old ``max(temps, 1e-6)`` scaled logits by 1e6, a needless
    overflow hazard on the never-used branch)."""
    t = jnp.where(temps > 0.0, temps, 1.0)[:, None]
    scaled = logits / t
    # kth-largest via a capped top-k (not a full [B, V] sort — V=32k sorts
    # cost milliseconds per step on TPU; see _MAX_TOP_K)
    kmax = min(_MAX_TOP_K, logits.shape[-1])
    topv, _ = jax.lax.top_k(scaled, kmax)
    idx = jnp.clip(top_ks - 1, 0, kmax - 1)
    kth = jnp.take_along_axis(topv, idx[:, None], axis=-1)
    return jnp.where((top_ks[:, None] > 0) & (scaled < kth), -1e30, scaled)


def _sample(logits, key, temps, top_ks):
    """Sample [B] token ids from [B, V] logits with *per-slot* traced
    sampling params — one compiled program serves any mix of greedy /
    temperature / top-k callers sharing the decode batch.

    temps [B] float32 (<= 0 -> greedy); top_ks [B] int32 (<= 0 -> off).

    temperature <= 0 is EXACT argmax of the raw logits: no temperature
    scaling, no top-k perturbation, and no dependence on ``key`` (the
    categorical draw happens on the other branch of the select; greedy
    rows ignore it entirely) — the enabling precondition for speculative
    decoding's greedy bit-parity pin (tests/test_specdec.py).
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    masked = _masked_scaled(logits, temps, top_ks)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


def _sample_dist(logits, temps, top_ks):
    """The probability distribution [B, V] that ``_sample`` draws from:
    post temperature/top-k softmax for temps > 0 rows, an exact one-hot
    at the argmax for greedy rows.  The one-hot form makes speculative
    rejection sampling COLLAPSE to exact greedy verification — accept iff
    the draft token is the target argmax, corrections/bonus tokens are
    the argmax — with no separate greedy branch in the verifier."""
    probs = jax.nn.softmax(_masked_scaled(logits, temps, top_ks), axis=-1)
    one_hot = jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1],
                             dtype=probs.dtype)
    return jnp.where(temps[:, None] <= 0.0, one_hot, probs)


def build_tp_mesh(cfg, tp: int):
    return build_engine_mesh(cfg, tp, 1)


def build_engine_mesh(cfg, tp: int, pp: int, mesh=None):
    """Validate the TP × PP degrees and build a `pipeline`×`tensor` mesh.

    TP=PP=1 stays mesh-free (single-device fast path).  PP shards the
    STACKED layer dim of params and KV cache over `pipeline`
    (vllm_models.py:181-191 folds the degree into placement; here it is a
    real mesh axis): each stage holds L/pp layers' weights + cache — the
    way to serve a model whose layers don't fit one chip/slice.  The
    layer scan crosses stage boundaries with XLA-inserted transfers of the
    [B, D] activation (tiny for decode); stages run sequentially within
    one step — PP here buys MEMORY reach, microbatch overlap is the
    training path's job (parallel/pipeline.py).

    ``mesh`` (LLMConfig.mesh): a caller-built mesh pinning WHICH devices
    the replica shards over — validated against the degrees (axis sizes
    must match) and the model's divisibility, then used as-is."""
    if tp <= 1 and pp <= 1 and mesh is None:
        return None
    if mesh is not None:
        shape = dict(mesh.shape)
        if shape.get("tensor", 1) != max(tp, 1):
            raise ValueError(
                f"config.mesh tensor axis is {shape.get('tensor', 1)} but "
                f"tensor_parallel_size={tp} — the degrees must agree")
        if shape.get("pipeline", 1) != max(pp, 1):
            raise ValueError(
                f"config.mesh pipeline axis is {shape.get('pipeline', 1)} "
                f"but pipeline_parallel_size={pp}")
    devices = jax.devices()
    if mesh is None and len(devices) < tp * pp:
        raise ValueError(
            f"tensor_parallel_size={tp} x pipeline_parallel_size={pp} needs "
            f"{tp * pp} devices but only {len(devices)} visible device(s) — "
            f"an engine must never silently compute on fewer chips than it "
            f"reserves")
    if cfg.n_layers % max(pp, 1):
        raise ValueError(
            f"pipeline_parallel_size={pp} does not divide n_layers={cfg.n_layers}")
    if tp > 1:
        for name, dim in (("n_heads", cfg.n_heads),
                          ("n_kv_heads", cfg.n_kv_heads),
                          ("ffn_dim", cfg.ffn_dim),
                          ("vocab_size", cfg.vocab_size)):
            if dim % tp:
                raise ValueError(
                    f"tensor_parallel_size={tp} does not divide model "
                    f"{name}={dim}")
    if mesh is not None:
        return mesh
    from ray_tpu.parallel.mesh import MeshSpec

    return MeshSpec(pipeline=pp, tensor=tp).build(devices[:tp * pp])


def pp_param_specs(specs: dict, pp: int) -> dict:
    """Shard the stacked-layer dim of inference params over `pipeline`."""
    if pp <= 1:
        return specs
    from jax.sharding import PartitionSpec as P

    specs = dict(specs)
    specs["layers"] = jax.tree.map(
        lambda s: P(*(("pipeline",) + tuple(s)[1:])), specs["layers"],
        is_leaf=lambda x: isinstance(x, P))
    return specs


def pp_cache_spec(spec: dict, pp: int) -> dict:
    """KV caches/pools are [L, ...]: shard dim 0 over `pipeline` too."""
    if pp <= 1:
        return spec
    from jax.sharding import PartitionSpec as P

    return {k: P(*(("pipeline",) + tuple(s)[1:])) for k, s in spec.items()}


def make_engine(config: "LLMConfig", params=None, *, key=None,
                draft_params=None):
    """Engine factory: ``config.kv_cache`` picks paged (default) or static.

    ``draft_params``: params for ``config.speculative_config``'s draft
    model (paged engine only; None with speculation configured random-
    initializes the draft — fine for tests, acceptance-rate ~0 in prod).
    """
    if config.kv_cache == "paged":
        from ray_tpu.llm.paged import PagedJaxLLMEngine

        return PagedJaxLLMEngine(config, params, key=key,
                                 draft_params=draft_params)
    if config.kv_cache == "static":
        if config.speculative_config is not None:
            raise ValueError(
                "speculative_config requires kv_cache='paged' (the static "
                "engine has no block pool for the draft KV)")
        return JaxLLMEngine(config, params, key=key)
    raise ValueError(
        f"kv_cache must be 'paged' or 'static' (got {config.kv_cache!r})")


class JaxLLMEngine:
    """Single-process engine owning params + cache on device.

    API: ``add_request() -> id``, ``step() -> {id: [new tokens]}``,
    ``generate()`` (sync convenience driving step() to completion).
    """

    def __init__(self, config: LLMConfig, params=None, *, key=None):
        self.config = config
        cfg = config.model_config
        if cfg is None:
            raise ValueError("LLMConfig.model_config is required")
        self.cfg = cfg
        self.max_batch = config.max_batch_size
        self.max_seq = config.max_seq_len or cfg.max_seq_len
        if config.decode_chunk < 1:
            # 0 would scan zero steps: step() emits nothing while
            # has_work() stays true — generate()/serve drivers spin forever
            raise ValueError(
                f"decode_chunk must be >= 1 (got {config.decode_chunk})")
        if params is None:
            params = llama.init_params(cfg, key or jax.random.PRNGKey(0))
        self.params = params
        cos, sin = rope_frequencies(cfg.head_dim, self.max_seq, cfg.rope_theta)
        self._rope = (jnp.asarray(cos), jnp.asarray(sin))

        # --- tensor parallelism: a real mesh, not just a chip reservation ---
        # (reference: vllm_models.py:177-186 wires TP from engine_kwargs into
        # the engine; here TP is a jax mesh axis and GSPMD partitions the
        # prefill/decode programs from the param + cache shardings alone)
        pp = config.pipeline_parallel_size
        self.mesh = build_engine_mesh(cfg, config.tensor_parallel_size, pp,
                                      mesh=getattr(config, "mesh", None))
        self.cache = llama.init_kv_cache(cfg, self.max_batch, self.max_seq)
        if self.mesh is not None:
            from ray_tpu.parallel.mesh import shard_pytree

            self.params = shard_pytree(
                self.params,
                pp_param_specs(llama.inference_param_specs(cfg), pp),
                self.mesh)
            self.cache = shard_pytree(
                self.cache, pp_cache_spec(llama.kv_cache_spec(), pp),
                self.mesh)
        # host-side slot state
        self._slot_req: List[Optional[_Request]] = [None] * self.max_batch
        self._lengths = np.zeros(self.max_batch, np.int32)
        self._next_tok = np.zeros(self.max_batch, np.int32)
        self._slot_temp = np.zeros(self.max_batch, np.float32)
        self._slot_topk = np.zeros(self.max_batch, np.int32)
        # device mirrors of the decode-loop state: the steady-state loop
        # must not upload ANYTHING per token, and the PRNG key lives on
        # device too (a host-side random.split measured 83ms on a tunneled
        # chip); mirrors refresh only on slot transitions
        self._dirty = True
        self._d_next = self._d_lengths = self._d_active = None
        self._d_temp = self._d_topk = None
        self._d_remaining = self._d_stops = None
        self._d_key = jax.random.PRNGKey(config.model_config.vocab_size + 1)
        self._pending: List[_Request] = []
        self._requests: Dict[int, _Request] = {}
        self._req_counter = 0
        self._lock = make_lock("JaxLLMEngine._lock")
        # one decode chunk may stay in flight (collected next step): its
        # readback overlaps the next chunk's compute, like the paged
        # engine.  (em_dev, active_slots).
        self._inflight = None
        # monotonic ts of the last traced step's phase spans (rate limit)
        self._last_phase_span = float("-inf")
        # serving deployment name (set via the replica's set_slo_label
        # threading); assigning one attaches device telemetry.  None
        # (direct engine use) keeps the disabled path: one attribute
        # read + None check per step.
        self._slo_label: Optional[str] = None
        self._telemetry: Optional[device_telemetry.EngineTelemetry] = None

        # params are an ARGUMENT of the jitted programs, never a closure:
        # captured closures lower as inline constants, and a real model's
        # weights (GBs) baked into the module stall compilation and double
        # HBM (observed: 2.3GB of captured constants on the 1B config)
        self._decode = jax.jit(self._decode_chunk_impl, donate_argnums=2,
                               static_argnums=10)
        # jax.jit caches per input shape, so bucketed prompt lengths reuse
        # compilations automatically
        self._prefill = jax.jit(self._prefill_impl)
        self._write_slot = jax.jit(llama.write_cache_slot, donate_argnums=0)

    def _build_tp_mesh(self, tp: int):
        return build_tp_mesh(self.cfg, tp)

    # -- device telemetry ----------------------------------------------

    @property
    def slo_label(self) -> Optional[str]:
        return self._slo_label

    @slo_label.setter
    def slo_label(self, name: Optional[str]) -> None:
        self._slo_label = name
        if name is None:
            self._telemetry = None
            return
        self._telemetry = device_telemetry.engine_telemetry_for(
            name,
            weights_bytes=device_telemetry.tree_nbytes(self.params),
            kv_pool_bytes=device_telemetry.tree_nbytes(self.cache))
        if self._telemetry is not None:
            device_telemetry.register_utilization_object(
                f"{name}:{id(self):x}", self)

    def utilization(self) -> dict:
        """Exact engine bookkeeping for ``state.utilization()``.  The
        static cache has no block pool — KV occupancy is slot occupancy
        (a slot owns its full max_seq stripe for its lifetime)."""
        with self._lock:
            active = sum(1 for r in self._slot_req if r is not None)
            pending = len(self._pending)
        row = {
            "engine": "static",
            "deployment": self._slo_label,
            "slots": {"active": active, "max": self.max_batch,
                      "free": self.max_batch - active},
            "kv_blocks": {"total": self.max_batch, "free":
                          self.max_batch - active, "used": active},
            "pending": pending,
        }
        tel = self._telemetry
        if tel is not None:
            rates = tel.rates()
            row["duty_cycle"] = rates["duty_cycle"]
            row["rates"] = rates
            row["hbm"] = tel.hbm_split()
        return row

    # -- jitted programs ------------------------------------------------

    def _decode_chunk_impl(self, params, tokens, cache, lengths, active,
                           remaining, stops, key, temps, top_ks, n_steps):
        """Advance every slot up to ``n_steps`` tokens in ONE program.

        Multi-step scheduling: stop-token / token-budget / cache-full
        handling runs in-program (slots self-deactivate mid-chunk), so the
        host syncs once per chunk instead of once per token — on a tunneled
        chip per-dispatch latency dwarfs the 1-token compute.
        Returns (emitted [n_steps, B] with -1 for inactive slots, new state).
        """

        def one(carry, _):
            tokens, cache, lengths, active, remaining, key = carry
            logits, cache = llama.decode_step(
                self.cfg, params, tokens, cache, lengths,
                rope_cache=self._rope)
            key, sub = jax.random.split(key)
            ids = _sample(logits, sub, temps, top_ks)
            emitted = jnp.where(active > 0, ids, -1)
            lengths = lengths + active
            remaining = remaining - active
            hit_stop = (stops == ids[:, None]).any(-1)
            done = (active > 0) & (hit_stop | (remaining <= 0)
                                   | (lengths + 1 >= self.max_seq))
            active = active * (1 - done.astype(active.dtype))
            tokens = jnp.where(active > 0, ids, tokens)
            return (tokens, cache, lengths, active, remaining, key), emitted

        carry = (tokens, cache, lengths, active, remaining, key)
        carry, emitted = jax.lax.scan(one, carry, None, length=n_steps)
        tokens, cache, lengths, active, remaining, key = carry
        return emitted, tokens, cache, lengths, active, remaining, key

    def _prefill_impl(self, params, tokens, length, key, temps, top_ks):
        logits, kv = llama.prefill(
            self.cfg, params, tokens, rope_cache=self._rope)
        last = logits[jnp.arange(tokens.shape[0]), length - 1]
        key, sub = jax.random.split(key)
        ids = _sample(last, sub, temps, top_ks)
        return ids, kv, key

    # -- request lifecycle ---------------------------------------------

    def add_request(self, prompt: Sequence[int],
                    gen: Optional[GenerationConfig] = None) -> int:
        gen = gen or GenerationConfig()
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(gen.stop_token_ids) > _MAX_STOP_IDS:
            raise ValueError(
                f"at most {_MAX_STOP_IDS} stop_token_ids supported "
                f"(got {len(gen.stop_token_ids)})")
        if gen.top_k > _MAX_TOP_K:
            raise ValueError(
                f"top_k is capped at {_MAX_TOP_K} (got {gen.top_k}) — the "
                "kth threshold comes from a fixed-width lax.top_k")
        if len(prompt) + gen.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({gen.max_new_tokens})"
                f" exceeds max_seq_len {self.max_seq}")
        with self._lock:
            self._req_counter += 1
            req = _Request(self._req_counter, list(prompt), gen)
            self._requests[req.request_id] = req
            self._pending.append(req)
            return req.request_id

    def has_work(self) -> bool:
        with self._lock:
            return (bool(self._pending) or self._inflight is not None
                    or any(r is not None for r in self._slot_req))

    def _admit_locked(self):
        """Prefill pending requests into free slots (continuous batching)."""
        for slot in range(self.max_batch):
            if not self._pending or self._slot_req[slot] is not None:
                continue
            req = self._pending.pop(0)
            plen = len(req.prompt)
            bucket = 1 << max(3, math.ceil(math.log2(plen)))
            bucket = min(bucket, self.max_seq)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :plen] = req.prompt
            ids, kv, self._d_key = self._prefill(
                self.params, jnp.asarray(tokens), jnp.asarray([plen]),
                self._d_key,
                jnp.asarray([req.gen.temperature], jnp.float32),
                jnp.asarray([req.gen.top_k], jnp.int32))
            self.cache = self._write_slot(self.cache, kv, slot)
            first = int(ids[0])
            req.slot = slot
            self._slot_req[slot] = req
            self._lengths[slot] = plen
            self._next_tok[slot] = first
            self._slot_temp[slot] = req.gen.temperature
            self._slot_topk[slot] = req.gen.top_k
            self._dirty = True  # device mirrors stale: new slot joined
            self._emit_locked(req, first)

    def _emit_locked(self, req: _Request, token: int):
        req.out_tokens.append(token)
        if (token in req.gen.stop_token_ids
                or len(req.out_tokens) >= req.gen.max_new_tokens
                or self._lengths[req.slot] + 1 >= self.max_seq):
            req.done = True
            self._slot_req[req.slot] = None
            self._lengths[req.slot] = 0
            req.slot = -1
            self._dirty = True  # device mirrors stale: slot freed

    def step(self, decode: bool = True) -> Dict[int, List[int]]:
        """Admit pending, then advance every active slot by up to
        ``config.decode_chunk`` tokens in one device program (multi-step
        scheduling; slots hitting a stop/budget mid-chunk deactivate
        in-program). decode_chunk=1 recovers per-token stepping.
        ``decode=False`` runs admission/prefill only (ramp control).

        Returns {request_id: [tokens emitted this step]}.
        """
        from ray_tpu.util import tracing

        # PhaseRecorder: spans stamped under the lock, emitted after
        # release (an emit_span GCS flush must not stall the decode path).
        # Rate-limited per engine (~5 span sets/s) so a steady traced
        # serving loop can't cycle the bounded GCS task sink.
        rec = tracing.PhaseRecorder()
        now = time.monotonic()
        traced = rec.active and now - self._last_phase_span >= 0.2
        if traced:
            self._last_phase_span = now
        # device telemetry: one attribute read + None check when disabled
        tel = self._telemetry
        tel_active = tel_pending = 0
        with self._lock:
            before = {id(r): len(r.out_tokens)
                      for r in self._requests.values()}
            if self._pending:
                # admission prefills synchronously; its cache writes chain
                # after any in-flight chunk on the cache dataflow, and the
                # new slot was inactive in that chunk (garbage rows are
                # overwritten by the decode step that first uses them)
                t_pf = time.time() if traced else 0.0
                self._admit_locked()
                if traced:
                    rec.stamp("engine.admit_prefill", t_pf)
            active = [s for s in range(self.max_batch)
                      if self._slot_req[s] is not None]
            if active and decode:
                if self._dirty:
                    self._collect_inflight_locked()
                    active = [s for s in range(self.max_batch)
                              if self._slot_req[s] is not None]
                if self._dirty and active:
                    # slot transition since last chunk: refresh the device
                    # mirrors from host truth — the ONLY uploads in the loop
                    self._d_next = jnp.asarray(self._next_tok)
                    self._d_lengths = jnp.asarray(self._lengths)
                    self._d_active = jnp.asarray(np.array(
                        [0 if r is None else 1 for r in self._slot_req],
                        np.int32))
                    self._d_temp = jnp.asarray(self._slot_temp)
                    self._d_topk = jnp.asarray(self._slot_topk)
                    remaining = np.zeros(self.max_batch, np.int32)
                    stops = np.full((self.max_batch, _MAX_STOP_IDS), -1,
                                    np.int32)
                    for s, r in enumerate(self._slot_req):
                        if r is not None:
                            remaining[s] = (r.gen.max_new_tokens
                                            - len(r.out_tokens))
                            for j, sid in enumerate(r.gen.stop_token_ids):
                                stops[s, j] = sid
                    self._d_remaining = jnp.asarray(remaining)
                    self._d_stops = jnp.asarray(stops)
                    self._dirty = False
            if active and decode:
                # one chunked decode program for the whole batch; sampling
                # params are traced per-slot arrays, so mixed greedy /
                # temperature / top-k callers share a single forward.
                # PIPELINED: the chunk dispatched here is collected next
                # step, its readback riding under this dispatch's compute.
                t_dec = time.time() if traced else 0.0
                (em_dev, self._d_next, self.cache, self._d_lengths,
                 self._d_active, self._d_remaining, self._d_key) = \
                    self._decode(
                        self.params, self._d_next, self.cache,
                        self._d_lengths, self._d_active, self._d_remaining,
                        self._d_stops, self._d_key, self._d_temp,
                        self._d_topk, self.config.decode_chunk)
                prev, self._inflight = self._inflight, (em_dev, active)
                if prev is not None:
                    self._book_chunk_locked(*prev)
                if traced:
                    rec.stamp("engine.decode", t_dec,
                              {"active_slots": len(active),
                               "chunk": self.config.decode_chunk})
            else:
                self._collect_inflight_locked()
            emitted = self._gather_emitted_locked(before)
            if tel is not None:
                # captured under the lock into locals; booked after
                # release next to rec.emit() (PhaseRecorder discipline)
                tel_active = sum(1 for r in self._slot_req
                                 if r is not None)
                tel_pending = len(self._pending)
        rec.emit()
        if tel is not None:
            t_end = time.monotonic()
            tel.note_step(
                active_slots=tel_active, max_slots=self.max_batch,
                free_blocks=self.max_batch - tel_active,
                total_blocks=self.max_batch, pending=tel_pending,
                prefill_spent=0, prefill_budget=0,
                busy_s=t_end - now, now=t_end)
        return emitted

    def _book_chunk_locked(self, em_dev, active):
        em = np.asarray(em_dev)  # [chunk, B] — the single sync
        for t in range(em.shape[0]):
            for s in active:
                req = self._slot_req[s]
                if req is None:
                    continue  # finished earlier in this chunk
                tok = int(em[t, s])
                if tok < 0:
                    continue
                self._lengths[s] += 1
                self._next_tok[s] = tok
                self._emit_locked(req, tok)

    def _collect_inflight_locked(self):
        if self._inflight is not None:
            em_dev, active = self._inflight
            self._inflight = None
            self._book_chunk_locked(em_dev, active)

    def _gather_emitted_locked(self, before):
        emitted: Dict[int, List[int]] = {}
        for req in list(self._requests.values()):
            n0 = before.get(id(req), 0)
            if len(req.out_tokens) > n0:
                emitted[req.request_id] = req.out_tokens[n0:]
            if req.done:
                del self._requests[req.request_id]
        return emitted

    def flush(self) -> Dict[int, List[int]]:
        """Collect any in-flight decode chunk and return its tokens."""
        with self._lock:
            before = {id(r): len(r.out_tokens)
                      for r in self._requests.values()}
            self._collect_inflight_locked()
            return self._gather_emitted_locked(before)

    def prefix_digest(self, max_hashes: Optional[int] = None) -> Dict:
        """Uniform engine surface for the cache-aware serve router: the
        static cache has no sharable prefix blocks, so its digest is empty
        (the router then treats every prompt as cold and uses pow-2)."""
        return {"block_size": 0, "hashes": []}

    # -- sync convenience ----------------------------------------------

    def generate(self, prompts: Sequence[Sequence[int]],
                 gen: Optional[GenerationConfig] = None) -> List[List[int]]:
        """Generate for a batch of prompts, driving step() to completion."""
        ids = [self.add_request(p, gen) for p in prompts]
        results: Dict[int, List[int]] = {i: [] for i in ids}
        waiting = set(ids)
        while waiting and self.has_work():
            emitted = self.step()
            for rid, toks in emitted.items():
                if rid in results:
                    results[rid].extend(toks)
            with self._lock:
                waiting = {rid for rid in waiting if rid in self._requests}
        # the last booking step may have dispatched one more (all-inactive)
        # chunk: collect it so has_work() is False on a drained engine
        self.flush()
        return [results[i] for i in ids]
