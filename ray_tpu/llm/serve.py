"""LLM serving: deployment wrapping the JAX engine with continuous batching.

reference: python/ray/llm/_internal/serve/deployments/llm/ — LLMServer
deployments on vLLM with per-replica placement groups sized from the
engine's TP/PP degrees (vllm_models.py:177-186, :241-259).  Here the
replica owns a JaxLLMEngine; concurrent requests enqueue into the engine
and a background thread drives ``engine.step()``, so all in-flight
requests share one decode batch (continuous batching across callers).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence  # noqa: F401

from ray_tpu.llm.config import GenerationConfig, LLMConfig


class LLMServer:
    """Deployment callable; bind with serve: see ``build_llm_deployment``."""

    def __init__(self, llm_config: LLMConfig, params=None):
        from ray_tpu.llm.engine import JaxLLMEngine

        self._engine = JaxLLMEngine(llm_config, params)
        self._cv = threading.Condition()
        self._done: Dict[int, List[int]] = {}
        self._waiters: Dict[int, List[int]] = {}
        self._stop = False
        self._error: Optional[BaseException] = None
        self._loop = threading.Thread(target=self._run, daemon=True,
                                      name="llm-engine-loop")
        self._loop.start()

    def _run(self):
        while not self._stop:
            if not self._engine.has_work():
                time.sleep(0.002)
                continue
            try:
                emitted = self._engine.step()
            except BaseException as e:  # noqa: BLE001 — fail waiters, not hang
                with self._cv:
                    self._error = e
                    self._cv.notify_all()
                return
            if emitted:
                with self._cv:
                    for rid, toks in emitted.items():
                        self._waiters.setdefault(rid, []).extend(toks)
                    with self._engine._lock:
                        live = set(self._engine._requests)
                    for rid in list(self._waiters):
                        if rid not in live:
                            self._done[rid] = self._waiters.pop(rid)
                    self._cv.notify_all()

    def shutdown(self):
        self._stop = True

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 top_k: int = 0, stop_token_ids: Sequence[int] = ()) -> List[int]:
        """Generate completion token ids for one prompt (sync; batching with
        concurrent callers happens inside the engine)."""
        gen = GenerationConfig(max_new_tokens=max_new_tokens,
                               temperature=temperature, top_k=top_k,
                               stop_token_ids=tuple(stop_token_ids))
        rid = self._engine.add_request(list(prompt), gen)
        with self._cv:
            while rid not in self._done:
                if self._error is not None:
                    raise RuntimeError("LLM engine loop failed") from self._error
                if self._stop:
                    raise RuntimeError("LLM server shut down")
                self._cv.wait(timeout=0.1)
            return self._done.pop(rid)

    def generate_stream(self, prompt: Sequence[int],
                        max_new_tokens: int = 64, temperature: float = 0.0,
                        top_k: int = 0, stop_token_ids: Sequence[int] = ()):
        """Yield token chunks AS DECODED — pair with
        ``.options(num_returns="streaming")`` on the actor method so callers
        iterate an ObjectRefGenerator while decoding continues (reference:
        vLLM streaming generate + serve streaming responses)."""
        gen = GenerationConfig(max_new_tokens=max_new_tokens,
                               temperature=temperature, top_k=top_k,
                               stop_token_ids=tuple(stop_token_ids))
        rid = self._engine.add_request(list(prompt), gen)
        sent = 0
        while True:
            with self._cv:
                while True:
                    if self._error is not None:
                        raise RuntimeError("LLM engine loop failed") from self._error
                    if self._stop:
                        raise RuntimeError("LLM server shut down")
                    done = rid in self._done
                    buf = self._done[rid] if done else self._waiters.get(rid, [])
                    if len(buf) > sent or done:
                        break
                    self._cv.wait(timeout=0.1)
                chunk = list(buf[sent:])
                sent += len(chunk)
                if done:
                    self._done.pop(rid, None)
            if chunk:
                yield chunk
            if done:
                return

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """HTTP-style entry: {"prompt": [ids], "max_new_tokens": n, ...}."""
        toks = self.generate(
            request["prompt"],
            max_new_tokens=request.get("max_new_tokens", 64),
            temperature=request.get("temperature", 0.0),
            top_k=request.get("top_k", 0),
            stop_token_ids=request.get("stop_token_ids", ()),
        )
        return {"tokens": toks}

    def check_health(self) -> bool:
        return self._loop.is_alive()


def build_llm_deployment(llm_config: LLMConfig, params=None, *,
                         name: str = "llm"):
    """An Application serving ``llm_config`` (reference:
    llm/_internal/serve build_openai_app / LLMServer deployment).

    Replica resources follow the engine's parallelism degrees the way the
    reference sizes placement groups from vLLM engine_kwargs.
    """
    from ray_tpu import serve

    deployment = serve.deployment(
        LLMServer,
        name=name,
        num_replicas=llm_config.num_replicas,
        # concurrent callers share the engine's decode batch
        max_ongoing_requests=max(8, llm_config.max_batch_size),
        ray_actor_options={"resources": llm_config.resources_per_replica()},
    )
    return deployment.bind(llm_config, params)
