"""LLM serving: deployment wrapping the JAX engine with continuous batching.

reference: python/ray/llm/_internal/serve/deployments/llm/ — LLMServer
deployments on vLLM with per-replica placement groups sized from the
engine's TP/PP degrees (vllm_models.py:177-186, :241-259).  Here the
replica owns a JaxLLMEngine; concurrent requests enqueue into the engine
and a background thread drives ``engine.step()``, so all in-flight
requests share one decode batch (continuous batching across callers).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence  # noqa: F401

from ray_tpu.llm.config import GenerationConfig, LLMConfig


def _jax_backend() -> str:
    import jax

    return jax.default_backend()


class LLMServer:
    """Deployment callable; bind with serve: see ``build_llm_deployment``.

    Multi-LoRA (reference: ray.llm's vLLM LoRA serving): ``lora_adapters``
    maps model ids to adapter pytrees (llm/lora.py). Each adapter gets its
    own engine over MERGED weights, created lazily on first request and all
    driven by the one loop — batched decode stays a single jitted program
    per engine, the right TPU trade (no per-slot adapter gathers)."""

    def __init__(self, llm_config: LLMConfig, params=None,
                 lora_adapters: Optional[Dict[str, Any]] = None,
                 draft_params=None):
        from ray_tpu.llm.engine import make_engine

        self._config = llm_config
        self._engine = make_engine(llm_config, params,
                                   draft_params=draft_params)
        # the MATERIALIZED draft weights (the engine random-initializes
        # when draft_params is None): per-adapter draft merges apply to
        # what actually runs, not the constructor argument
        self._draft_params = getattr(self._engine, "_draft_params",
                                     draft_params)
        if hasattr(self._engine, "warmup") and _jax_backend() == "tpu":
            # compile every decode (B, W) bucket before serving traffic —
            # a bucket transition otherwise costs a multi-second XLA
            # compile inside the latency path (vLLM warms shapes at
            # startup the same way)
            self._engine.warmup()
        self._engines: Dict[Optional[str], Any] = {None: self._engine}
        self._engine_gen: Dict[Optional[str], int] = {None: 0}
        self._engine_order: list = []  # adapter LRU (base never evicted)
        self._adapters: Dict[str, Any] = dict(lora_adapters or {})
        self._engines_lock = threading.Lock()
        # held by the _run loop across each step + token-apply pair, and
        # by export/cancel across their engine drain + waiter reconcile:
        # a drain landing between a step's gather and its apply would
        # otherwise double-deliver the step's delta (see _reap_drained)
        self._step_lock = threading.Lock()
        self._cv = threading.Condition()
        self._done: Dict[Any, List[int]] = {}
        self._waiters: Dict[Any, List[int]] = {}
        # wkeys some caller is still consuming — eviction cleanup must not
        # delete their results out from under them (guarded by _cv's lock)
        self._active_waiters: set = set()
        # wkeys aborted mid-stream (client disconnect): one trailing
        # emission batch may still surface after the engine cancel — it
        # must not recreate the popped waiter entry as a leaked _done row
        # (guarded by _cv's lock; bounded by the clear-cap below)
        self._aborted: set = set()
        # wkeys mid-migration (serve/_private/kv_migration.py): their
        # engine request is being (or has been) exported away, so the
        # _run loop must neither re-apply their history nor declare them
        # done when the rid leaves the engine — the splice relay owns
        # their buffer lifecycle (guarded by _cv's lock)
        self._migrating: set = set()
        # mig_id -> import result memo (idempotent migration retries;
        # guarded by _cv's lock, bounded)
        self._mig_imports: Dict[str, Any] = {}
        self._stop = False
        self._error: Optional[BaseException] = None
        self._loop = threading.Thread(target=self._run, daemon=True,
                                      name="llm-engine-loop")
        self._loop.start()

    _slo_label: Optional[str] = None

    def lora_model_ids(self) -> List[str]:
        return sorted(self._adapters)

    def set_slo_label(self, name: str) -> None:
        """Serving SLO layer threading (serve/_private/replica.py): label
        this server's engines with the hosting deployment's name so
        engine-side lifecycle stages (queue_wait, prefill, decode) book
        under it.  Unlabeled servers (direct library use) book nothing."""
        self._slo_label = name
        for eng in list(self._engines.values()):
            try:
                eng.slo_label = name
            except Exception:  # noqa: BLE001 — static engine variants
                pass

    def utilization(self) -> Optional[Dict[str, Any]]:
        """Device-telemetry utilization row for the hosting replica's
        publish loop and the local-mode fold (state.utilization()): the
        base engine's exact bookkeeping, plus any live adapter engines'
        rows under ``adapters``.  ``None`` when the engine variant has no
        utilization surface."""
        base = getattr(self._engine, "utilization", None)
        row = base() if base is not None else None
        if row is None:
            return None
        with self._engines_lock:
            extras = [(m, e) for m, e in self._engines.items()
                      if m is not None]
        adapters = {}
        for model, eng in extras:
            try:
                adapters[model] = eng.utilization()
            except Exception:  # noqa: BLE001 — engine variants without one
                pass
        if adapters:
            row["adapters"] = adapters
        if self._slo_label is not None:
            row["deployment"] = self._slo_label
        return row

    def prefix_digest(self) -> Dict[str, Any]:
        """Cache-aware routing surface (serve/handle.py): the base engine's
        prefix-chain digest plus the adapter ids this replica has loaded
        (LoRA affinity) and the live request depth.  Published to the GCS
        KV by the hosting replica (throttled, versioned)."""
        digest = getattr(self._engine, "prefix_digest", lambda: {})() or {}
        with self._engines_lock:
            engines = list(self._engines.values())
            models = [m for m in self._engine_order]
        qlen = 0
        for eng in engines:
            try:
                with eng._lock:
                    qlen += len(eng._requests)
            except Exception:  # noqa: BLE001 — engine variants without a request table are legal
                pass
        digest["models"] = models
        digest["qlen"] = qlen
        return digest

    def _wait_done(self, wkey) -> List[int]:
        """Block until ``wkey``'s request finishes; return all its tokens."""
        try:
            with self._cv:
                while wkey not in self._done:
                    if self._error is not None:
                        raise RuntimeError(
                            "LLM engine loop failed") from self._error
                    if self._stop:
                        raise RuntimeError("LLM server shut down")
                    self._cv.wait(timeout=0.1)
                buf = self._done.pop(wkey)
            self._note_specdec(wkey)
            return buf
        finally:
            with self._cv:
                self._active_waiters.discard(wkey)

    def _note_specdec(self, wkey) -> None:
        """Attach a finished request's speculative acceptance (engine-side
        per-request stats) to the active SLO tracker's recent-row.  A
        no-op for non-speculative engines, unknown ids, or callers with
        no tracker context — never raises into the serving path.

        Tracker context is thread-local and ingress-side (see
        slo.note_specdec_request): the row field lands for local-mode
        streaming and handle-level callers under ``slo.activate``; a
        cluster-mode replica process has no tracker and relies on the
        ledger fold + metric families for the acceptance signal."""
        model, gen_id, rid = wkey
        try:
            if model is None:
                eng = self._engine
            else:
                with self._engines_lock:
                    eng = (self._engines.get(model)
                           if self._engine_gen.get(model, 0) == gen_id
                           else None)
            stats = getattr(eng, "specdec_request_stats",
                            lambda _rid: None)(rid)
        except Exception:  # noqa: BLE001
            stats = None
        if stats:
            from ray_tpu.serve._private import slo

            slo.note_specdec_request(stats[0], stats[1])

    def _iter_tokens(self, wkey):
        """Yield ``wkey``'s token chunks as they decode (generate_stream's
        engine-side loop, shared with the disaggregated decode stage).

        Closing the generator BEFORE exhaustion (the caller's client
        disconnected — the proxy closes the stream chain) aborts the
        engine-side request: its slot and KV blocks return to the pool
        immediately instead of decoding to max_new_tokens for nobody."""
        sent = 0
        completed = False
        try:
            while True:
                with self._cv:
                    while True:
                        if self._error is not None:
                            raise RuntimeError(
                                "LLM engine loop failed") from self._error
                        if self._stop:
                            raise RuntimeError("LLM server shut down")
                        done = wkey in self._done
                        buf = (self._done[wkey] if done
                               else self._waiters.get(wkey, []))
                        if len(buf) > sent or done:
                            break
                        self._cv.wait(timeout=0.1)
                    chunk = list(buf[sent:])
                    sent += len(chunk)
                    if done:
                        self._done.pop(wkey, None)
                if chunk:
                    yield chunk
                if done:
                    completed = True
                    self._note_specdec(wkey)
                    return
        finally:
            if not completed:
                self._abort_wkey(wkey)
            with self._cv:
                self._active_waiters.discard(wkey)

    def _abort_wkey(self, wkey) -> None:
        """Cancel ``wkey``'s engine request and drop its buffers (stream
        abandoned mid-decode).  Best-effort: a request that finished in
        the race just cleans its unclaimed buffers."""
        model, gen_id, rid = wkey
        if model is None:
            # the cancel's drain resolves the in-flight chunk for EVERY
            # slot — run it atomically vs the loop's step+apply and
            # reconcile bystander buffers after (see _reap_drained)
            with self._step_lock:
                try:
                    cancel = getattr(self._engine, "cancel_request", None)
                    if cancel is not None:
                        cancel(rid)
                except Exception:  # noqa: BLE001 — abort must never mask the close
                    pass
                with self._cv:
                    self._waiters.pop(wkey, None)
                    self._done.pop(wkey, None)
                    self._aborted.add(wkey)
                    if len(self._aborted) > 4096:  # backstop
                        self._aborted.clear()
                self._reap_drained()
            return
        try:
            with self._engines_lock:
                eng = (self._engines.get(model)
                       if self._engine_gen.get(model, 0) == gen_id
                       else None)
            cancel = getattr(eng, "cancel_request", None)
            if cancel is not None:
                cancel(rid)
        except Exception:  # noqa: BLE001 — abort must never mask the close
            pass
        with self._cv:
            self._waiters.pop(wkey, None)
            self._done.pop(wkey, None)
            self._aborted.add(wkey)
            if len(self._aborted) > 4096:  # never-seen-again backstop
                self._aborted.clear()

    _MAX_ADAPTER_ENGINES = 4

    def _submit(self, model: Optional[str], prompt, gen):
        """Resolve the engine for ``model`` and enqueue the request under
        ONE _engines_lock critical section, returning the waiter key.

        Invariants this protects (each was a bug once):
          - the merge + XLA compile happens OUTSIDE the lock (the _run loop
            takes it every iteration; compiling under it would freeze every
            in-flight stream);
          - add_request runs while holding the lock, so the eviction scan
            (which only removes engines with has_work() false, also under
            the lock) can never orphan a just-submitted request;
          - waiter keys carry the engine's BUILD GENERATION: a rebuilt
            engine restarts its request-id counter, and without the gen a
            new request could collide with an abandoned one's buffers."""
        if not model or model not in self._adapters:
            # base engine is never evicted, so its waiters need no registry
            return (None, 0, self._engine.add_request(prompt, gen))
        built = None
        while True:
            with self._engines_lock:
                eng = self._engines.get(model)
                if eng is None and built is not None:
                    self._engine_gen[model] = self._engine_gen.get(model, 0) + 1
                    if self._slo_label is not None:
                        try:
                            built.slo_label = self._slo_label
                        except Exception:  # noqa: BLE001 — engine variants without SLO threading are legal
                            pass
                    self._engines[model] = eng = built
                if eng is not None:
                    rid = eng.add_request(prompt, gen)
                    wkey = (model, self._engine_gen[model], rid)
                    with self._cv:
                        self._active_waiters.add(wkey)
                    if model in self._engine_order:
                        self._engine_order.remove(model)
                    self._engine_order.append(model)
                    self._evict_idle_locked(keep=model)
                    return wkey
            # build outside the lock: merged weights are owned solely by the
            # engine map (single LRU bounds HBM)
            import dataclasses

            from ray_tpu.llm.engine import make_engine
            from ray_tpu.llm.lora import adapter_speculation, merge_lora

            # per-adapter draft choice (the multi-LoRA extension of
            # speculative decoding): an adapter may opt out, override k,
            # or carry its own draft-model LoRA so the draft tracks the
            # tuned target
            spec_cfg, draft_adapter = adapter_speculation(
                self._config.speculative_config, model)
            cfg = self._config
            if spec_cfg is not self._config.speculative_config:
                cfg = dataclasses.replace(cfg, speculative_config=spec_cfg)
            # tensor-parallel replicas: the merged-weight adapter engine
            # must shard over the SAME mesh as the base engine — a fresh
            # mesh built from tensor_parallel_size over "first visible
            # devices" could pick different chips than a placement-group
            # pinned base, double-committing HBM on one slice while the
            # reserved one idles
            base_mesh = getattr(self._engine, "mesh", None)
            if base_mesh is not None and cfg.mesh is None:
                cfg = dataclasses.replace(cfg, mesh=base_mesh)
            dparams = self._draft_params
            if spec_cfg is not None and draft_adapter is not None:
                dparams = merge_lora(self._draft_params, draft_adapter)
            built = make_engine(
                cfg, merge_lora(self._engine.params,
                                self._adapters[model]),
                draft_params=dparams)

    def _evict_idle_locked(self, keep):
        extra = len(self._engine_order) - self._MAX_ADAPTER_ENGINES
        for name in list(self._engine_order):
            if extra <= 0:
                break
            if name != keep and not self._engines[name].has_work():
                del self._engines[name]
                self._engine_order.remove(name)
                extra -= 1
                # drop the evicted engine's ABANDONED result buffers only:
                # a finished-but-unclaimed result may still have a live
                # caller between cv polls — never delete under a waiter
                with self._cv:
                    for wkey in [k for k in self._done
                                 if k[0] == name and k not in self._active_waiters]:
                        del self._done[wkey]
                    for wkey in [k for k in self._waiters
                                 if k[0] == name and k not in self._active_waiters]:
                        del self._waiters[wkey]

    def _run(self):
        while not self._stop:
            with self._engines_lock:
                engines = list(self._engines.items())
            worked = False
            for key, engine in engines:
                if not engine.has_work():
                    continue
                worked = True
                gen_id = self._engine_gen.get(key, 0)
                # step + apply are one atomic unit vs export/cancel
                # drains: a drain between the step's snapshot-delta
                # gather and this apply would reconcile the buffer to
                # full history and then have the stale delta re-appended
                with self._step_lock:
                    try:
                        emitted = engine.step()
                    except BaseException as e:  # noqa: BLE001 — fail waiters, not hang
                        with self._cv:
                            self._error = e
                            self._cv.notify_all()
                        return
                    if emitted:
                        with self._cv:
                            for rid, toks in emitted.items():
                                wk = (key, gen_id, rid)
                                if wk in self._migrating:
                                    # an export is reconciling this
                                    # stream's history into its buffer —
                                    # these tokens are already part of
                                    # the handoff
                                    continue
                                if wk in self._aborted:
                                    self._aborted.discard(wk)
                                    continue
                                self._waiters.setdefault(wk, []).extend(
                                    toks)
                            with engine._lock:
                                live = set(engine._requests)
                            for wkey in list(self._waiters):
                                if (wkey[0] == key and wkey[1] == gen_id
                                        and wkey[2] not in live
                                        and wkey not in self._migrating):
                                    buf = self._waiters.pop(wkey)
                                    if wkey in self._aborted:
                                        self._aborted.discard(wkey)
                                    else:
                                        self._done[wkey] = buf
                            self._cv.notify_all()
            if not worked:
                time.sleep(0.002)

    # -- live KV migration (serve/_private/kv_migration.py) -------------
    #
    # A live stream moves between decode replicas in phases: the SOURCE
    # exports the engine request (export_stream — the slot and KV blocks
    # free immediately), the handoff travels to the DESTINATION
    # (import_migration — scatter + draft re-seed, or recompute), and the
    # source installs a relay (_splice) that keeps feeding the client's
    # ORIGINAL waiter buffer from the destination's continuation stream
    # (resume_stream).  The client's _iter_tokens never observes the
    # switch; the source lingers only as a thin byte relay until its
    # spliced streams finish — its engine is empty.

    def migratable_streams(self) -> List[int]:
        """Base-engine request ids currently in the exportable state
        (prefill complete, >= 1 token emitted).  Adapter streams are not
        listed — they carry no base-pool KV and resume on a destination
        by recompute through the planner's recompute path."""
        eng = self._engine
        if not hasattr(eng, "export_request"):
            return []
        out: List[int] = []
        with eng._lock:
            for rid, req in eng._requests.items():
                if (not req.done and req.slot >= 0
                        and req.prefill_pos >= len(req.prompt)
                        and req.out_tokens):
                    out.append(rid)
        return out

    def export_stream(self, rid: int) -> Dict[str, Any]:
        """Source-side migration export: drain + export ``rid`` from the
        base engine and reconcile the waiter buffer with the handoff's
        authoritative token history (the export's drain may resolve
        tokens the _run loop never gathered; marking the wkey migrating
        first makes the reconcile race-free against the loop).  On ANY
        failure the stream is healed back to normal operation — tokens
        re-synced from the engine, migration mark dropped — and the
        error re-raised for the planner's retry ladder."""
        wkey = (None, 0, rid)
        with self._cv:
            self._migrating.add(wkey)
        with self._step_lock:
            try:
                h = self._engine.export_request(rid)
            except BaseException:
                # export refused/died: the request may still be live in
                # the engine.  Re-sync the waiter buffer from engine
                # truth (the loop skipped emissions while the wkey was
                # marked) and hand the stream back to the normal path.
                with self._cv:
                    self._migrating.discard(wkey)
                    if wkey not in self._aborted:
                        with self._engine._lock:
                            req = self._engine._requests.get(rid)
                            hist = (list(req.out_tokens)
                                    if req is not None and not req.done
                                    else None)
                        if hist is not None:
                            buf = self._waiters.setdefault(wkey, [])
                            if len(hist) > len(buf):
                                buf.extend(hist[len(buf):])
                        self._cv.notify_all()
                self._reap_drained()  # rid, if the drain completed it
                raise
            h["model"] = None
            with self._cv:
                if wkey in self._aborted:
                    # client vanished during the export — nothing to
                    # splice
                    self._migrating.discard(wkey)
                else:
                    buf = self._waiters.setdefault(wkey, [])
                    if len(h["emitted"]) > len(buf):
                        buf.extend(h["emitted"][len(buf):])
                        self._cv.notify_all()
            # OTHER streams: the drain resolved their in-flight chunk
            # (and may have completed some) — reconcile before the loop
            # resumes stepping
            self._reap_drained()
        return h

    def _reap_drained(self) -> None:
        """Reconcile waiter buffers after an export/cancel drain.  The
        drain resolves the in-flight decode chunk for EVERY slot, and
        ``step()`` reports tokens as a snapshot delta taken at step
        entry — tokens a drain appended to ``out_tokens`` are invisible
        to all future deltas, so without this sync bystander streams
        silently lose one chunk.  A waiter buffer is always a prefix of
        its request's ``out_tokens`` (both are append-only, the loop
        extends from snapshot diffs), so topping up is bit-exact.
        Requests the drain COMPLETED are also moved to done here: once
        every slot is free ``has_work`` goes false and the loop would
        never gather them, hanging their consumers.  Mid-migration wkeys
        are skipped (their splice relay owns the buffer); aborted wkeys
        just clear their mark."""
        with self._cv:
            with self._engine._lock:
                dead, live = [], []
                for rid, req in list(self._engine._requests.items()):
                    wk = (None, 0, rid)
                    if wk in self._migrating:
                        continue
                    if req.done:
                        dead.append((wk, list(req.out_tokens)))
                        del self._engine._requests[rid]
                    elif req.out_tokens:
                        live.append((wk, list(req.out_tokens)))
            for wk, hist in live:
                if wk in self._aborted:
                    continue
                buf = self._waiters.setdefault(wk, [])
                if len(hist) > len(buf):
                    buf.extend(hist[len(buf):])
            for wk, hist in dead:
                if wk in self._aborted:
                    self._aborted.discard(wk)
                    self._waiters.pop(wk, None)
                    continue
                buf = self._waiters.setdefault(wk, [])
                if len(hist) > len(buf):
                    buf.extend(hist[len(buf):])
                self._done[wk] = self._waiters.pop(wk)
            if dead or live:
                self._cv.notify_all()

    @staticmethod
    def _handoff_gen(handoff: Dict[str, Any],
                     max_new_tokens: Optional[int] = None):
        g = handoff["gen"]
        return GenerationConfig(
            max_new_tokens=(g["max_new_tokens"] if max_new_tokens is None
                            else max_new_tokens),
            temperature=g["temperature"], top_k=g["top_k"],
            seed=g.get("seed", 0),
            stop_token_ids=tuple(g["stop_token_ids"]))

    def import_migration(self, handoff: Dict[str, Any],
                         allow_recompute: bool = False):
        """Destination-side migration import.  Tries the exact-resume KV
        import first (zero recompute); ``allow_recompute`` falls back to
        re-prefilling prompt + history as a fresh request with the
        remaining token budget (bit-equal for greedy decode — emitted
        history is never re-emitted either way).  Returns
        {wkey, done, mode} or None when this replica can't take the
        stream right now (no slot / no blocks) — the planner tries the
        next candidate.

        Idempotent under retry: the handoff's ``mig_id`` keys a bounded
        result memo, so a planner retrying after a lost reply gets the
        FIRST import's stream back instead of forking a duplicate."""
        mig_id = handoff.get("mig_id")
        if mig_id is not None:
            with self._cv:
                prev = self._mig_imports.get(mig_id)
            if prev is not None:
                return prev
        model = handoff.get("model")
        emitted = [int(t) for t in handoff["emitted"]]
        res = None
        if (not model and handoff.get("k") is not None
                and hasattr(self._engine, "import_request")):
            try:
                res = self._engine.import_request(
                    handoff["prompt"], handoff["first_token"],
                    handoff["k"], handoff["v"], self._handoff_gen(handoff),
                    emitted=emitted)
            except ValueError:
                # geometry mismatch (block size / max_seq) — recompute
                # is the only road
                res = None
        if res is not None:
            wkey = (None, 0, res["request_id"])
            out = {"wkey": list(wkey), "done": bool(res["done"]),
                   "mode": "import"}
            with self._cv:
                self._active_waiters.add(wkey)
                if res["done"]:
                    # budget/stop boundary hit exactly at the handoff:
                    # the continuation stream is empty but must exist
                    self._done[wkey] = []
                    self._cv.notify_all()
                self._memo_import_locked(mig_id, out)
            return out
        if not allow_recompute:
            return None
        out = self._recompute_resume(model, handoff)
        if out is not None:
            with self._cv:
                self._memo_import_locked(mig_id, out)
        return out

    def _memo_import_locked(self, mig_id, result) -> None:
        if mig_id is None:
            return
        self._mig_imports[mig_id] = result
        while len(self._mig_imports) > 1024:  # bounded retry memo
            self._mig_imports.pop(next(iter(self._mig_imports)))

    def _recompute_resume(self, model: Optional[str],
                          handoff: Dict[str, Any]):
        """Resume a migrated stream WITHOUT its KV: re-prefill
        prompt + emitted history as a fresh request whose budget is the
        remaining tokens (PR 7's degraded-handoff path; the prefix cache
        usually absorbs most of the re-prefill).  History is the new
        prompt's tail, so nothing is ever re-emitted."""
        hist = [int(t) for t in handoff["emitted"]]
        g = handoff["gen"]
        remaining = int(g["max_new_tokens"]) - len(hist)
        if remaining <= 0 or (hist and hist[-1] in g["stop_token_ids"]):
            return {"wkey": None, "done": True, "mode": "recompute"}
        gen = self._handoff_gen(handoff, max_new_tokens=remaining)
        wkey = self._submit(model, list(handoff["prompt"]) + hist, gen)
        with self._cv:
            self._active_waiters.add(wkey)
        return {"wkey": list(wkey), "done": False, "mode": "recompute"}

    def resume_stream(self, wkey):
        """Destination-side continuation stream for a migrated-in
        request: yields only tokens decoded AFTER the handoff point
        (the source already streamed the history)."""
        yield from self._iter_tokens(tuple(wkey))

    def cancel_stream(self, wkey) -> None:
        """Abort a migrated-in stream (the source's client vanished, or
        a splice fallback abandoned this destination)."""
        self._abort_wkey(tuple(wkey))

    def _splice(self, rid: int, pull, cancel_remote,
                handoff: Dict[str, Any]) -> threading.Thread:
        """Install the waiter-splice for an exported stream: a relay
        thread feeds the client's ORIGINAL waiter buffer (old wkey) from
        ``pull`` — an iterator of continuation chunks from the migration
        destination (or a local restore).  If the destination dies
        mid-relay, the stream degrades once to local recompute from
        prompt + delivered history (the survivor in that case is this
        replica): zero client-visible drops, at re-prefill cost."""
        wkey = (None, 0, rid)
        hist = [int(t) for t in handoff["emitted"]]
        g = dict(handoff["gen"])

        def run():
            from ray_tpu._private import runtime_metrics

            it = pull
            fell_back = False
            while True:
                try:
                    for chunk in it:
                        toks = [int(t) for t in chunk]
                        with self._cv:
                            if wkey in self._aborted:
                                for fn in (getattr(it, "close", None),
                                           cancel_remote):
                                    try:
                                        if fn is not None:
                                            fn()
                                    except Exception:  # noqa: BLE001 — abort cleanup is best-effort
                                        pass
                                self._migrating.discard(wkey)
                                return
                            hist.extend(toks)
                            self._waiters.setdefault(wkey, []).extend(toks)
                            self._cv.notify_all()
                    break  # destination stream completed cleanly
                except Exception:  # noqa: BLE001 — dest died mid-relay: degrade, don't drop
                    if fell_back:
                        break  # local fallback failed too: terminate below
                    fell_back = True
                    runtime_metrics.record_kv_migration(
                        handoff.get("reason", "manual"), "fallback")
                    remaining = int(g["max_new_tokens"]) - len(hist)
                    if remaining <= 0:
                        break
                    try:
                        new_wkey = self._submit(
                            None, list(handoff["prompt"]) + hist,
                            self._handoff_gen(handoff,
                                              max_new_tokens=remaining))
                    except Exception:  # noqa: BLE001 — even local admission failed
                        break
                    it = self._iter_tokens(new_wkey)
            with self._cv:
                self._migrating.discard(wkey)
                if wkey not in self._aborted:
                    self._done[wkey] = self._waiters.pop(wkey, [])
                    self._cv.notify_all()

        t = threading.Thread(target=run, daemon=True,
                             name="kv-migration-splice")
        t.start()
        return t

    def _finish_migrated(self, rid: int) -> None:
        """Terminate an exported stream whose continuation is EMPTY (the
        budget/stop boundary landed exactly on the handoff): the waiter
        buffer already holds the full history, so just finish it."""
        wkey = (None, 0, rid)
        with self._cv:
            self._migrating.discard(wkey)
            if wkey not in self._aborted:
                self._done[wkey] = self._waiters.pop(wkey, [])
                self._cv.notify_all()

    def evacuate_streams(self, dests=None, reason: str = "drain",
                         max_streams: Optional[int] = None,
                         dest_servers=None) -> Dict[str, int]:
        """Migrate this server's live base-engine streams to ``dests``
        (replica actor-id hexes; ``dest_servers`` takes in-process
        LLMServer objects for local mode and tests).  The planner's
        entry point for drain evacuation and rebalancing; every stream
        survives — worst case it stays here via local restore."""
        from ray_tpu.serve._private import kv_migration

        return kv_migration.evacuate(self, dests or [], reason=reason,
                                     max_streams=max_streams,
                                     dest_servers=dest_servers)

    def shutdown(self):
        self._stop = True

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: int = 64, temperature: float = 0.0,
                 top_k: int = 0, stop_token_ids: Sequence[int] = (),
                 model: Optional[str] = None) -> List[int]:
        """Generate completion token ids for one prompt (sync; batching with
        concurrent callers happens inside the engine). ``model`` selects a
        registered LoRA adapter (None/base id -> base weights)."""
        gen = GenerationConfig(max_new_tokens=max_new_tokens,
                               temperature=temperature, top_k=top_k,
                               stop_token_ids=tuple(stop_token_ids))
        wkey = self._submit(model, list(prompt), gen)
        return self._wait_done(wkey)

    def generate_stream(self, prompt: Sequence[int],
                        max_new_tokens: int = 64, temperature: float = 0.0,
                        top_k: int = 0, stop_token_ids: Sequence[int] = (),
                        model: Optional[str] = None):
        """Yield token chunks AS DECODED — pair with
        ``.options(num_returns="streaming")`` on the actor method so callers
        iterate an ObjectRefGenerator while decoding continues (reference:
        vLLM streaming generate + serve streaming responses)."""
        gen = GenerationConfig(max_new_tokens=max_new_tokens,
                               temperature=temperature, top_k=top_k,
                               stop_token_ids=tuple(stop_token_ids))
        wkey = self._submit(model, list(prompt), gen)
        yield from self._iter_tokens(wkey)

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """HTTP-style entry: {"prompt": [ids], "max_new_tokens": n, ...}."""
        toks = self.generate(
            request["prompt"],
            max_new_tokens=request.get("max_new_tokens", 64),
            temperature=request.get("temperature", 0.0),
            top_k=request.get("top_k", 0),
            stop_token_ids=request.get("stop_token_ids", ()),
            model=request.get("model"),
        )
        return {"tokens": toks}

    def check_health(self) -> bool:
        return self._loop.is_alive()


def build_llm_deployment(llm_config: LLMConfig, params=None, *,
                         name: str = "llm",
                         lora_adapters: Optional[Dict[str, Any]] = None,
                         draft_params=None):
    """An Application serving ``llm_config`` (reference:
    llm/_internal/serve build_openai_app / LLMServer deployment).

    Replica resources follow the engine's parallelism degrees the way the
    reference sizes placement groups from vLLM engine_kwargs.
    ``draft_params``: weights for ``llm_config.speculative_config``'s
    draft model (ignored without a speculative config).
    """
    from ray_tpu import serve

    deployment = serve.deployment(
        LLMServer,
        name=name,
        num_replicas=llm_config.num_replicas,
        # concurrent callers share the engine's decode batch
        max_ongoing_requests=max(8, llm_config.max_batch_size),
        ray_actor_options={"resources": llm_config.resources_per_replica()},
    )
    return deployment.bind(llm_config, params, lora_adapters, draft_params)
